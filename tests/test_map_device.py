"""Device mAP evaluator (``MeanAveragePrecision(backend="device")``) vs the host
oracle: parity fuzz across the COCO knobs (iscrowd, user areas, custom maxDets,
degenerate boxes, empty images), the fixed-capacity sentinels, merge/reset
semantics, and the mapeval AOT warm-start path.

Parity tolerance is 1e-4: the device program evaluates in f32 (IoU thresholds
are quantized identically on both sides), the host oracle accumulates in f64.
"""

from __future__ import annotations

import numpy as np
import pytest

import torchmetrics_tpu
from torchmetrics_tpu import aot
from torchmetrics_tpu.detection import DeviceMeanAveragePrecision, MeanAveragePrecision
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

pytestmark = pytest.mark.detection

ATOL = 1e-4


def _rand_dataset(
    rng,
    n_imgs: int = 9,
    n_cls: int = 6,
    max_det: int = 12,
    max_gt: int = 8,
    crowd_rate: float = 0.0,
    area_rate: float = 0.0,
    degenerate_rate: float = 0.0,
    empty_rate: float = 0.15,
    canvas: float = 120.0,
):
    """One batch of COCO-shaped preds/targets exercising the requested knobs."""
    preds, target = [], []
    for _ in range(n_imgs):
        nd = 0 if rng.random() < empty_rate else int(rng.integers(1, max_det + 1))
        ng = 0 if rng.random() < empty_rate else int(rng.integers(1, max_gt + 1))
        xy = rng.uniform(0, canvas, (nd, 2))
        wh = rng.uniform(2, 60, (nd, 2))
        boxes = np.concatenate([xy, xy + wh], -1).astype(np.float32)
        if degenerate_rate and nd:
            flip = rng.random(nd) < degenerate_rate  # zero/negative extent boxes
            boxes[flip] = boxes[flip][:, [2, 3, 0, 1]]
        preds.append({
            "boxes": boxes,
            "scores": rng.uniform(0, 1, nd).astype(np.float32),
            "labels": rng.integers(0, n_cls, nd).astype(np.int32),
        })
        xy = rng.uniform(0, canvas, (ng, 2))
        wh = rng.uniform(2, 60, (ng, 2))
        tgt = {
            "boxes": np.concatenate([xy, xy + wh], -1).astype(np.float32),
            "labels": rng.integers(0, n_cls, ng).astype(np.int32),
        }
        if crowd_rate:
            tgt["iscrowd"] = (rng.random(ng) < crowd_rate).astype(np.int32)
        if area_rate:
            area = (wh[:, 0] * wh[:, 1]).astype(np.float32)
            use = rng.random(ng) < area_rate
            tgt["area"] = np.where(use, area * rng.uniform(0.2, 30.0, ng).astype(np.float32), 0.0)
        target.append(tgt)
    return preds, target


def _assert_parity(host_out, dev_out, class_metrics=False, last_mdet=100):
    for key, val in host_out.items():
        arr = np.asarray(val)
        if arr.ndim == 0 and arr.dtype.kind == "f":
            assert abs(float(val) - float(dev_out[key])) <= ATOL, (
                f"{key}: host={float(val)} device={float(dev_out[key])}"
            )
    if class_metrics:
        np.testing.assert_array_equal(np.asarray(host_out["classes"]), np.asarray(dev_out["classes"]))
        for key in ("map_per_class", f"mar_{last_mdet}_per_class"):
            np.testing.assert_allclose(
                np.asarray(dev_out[key]), np.asarray(host_out[key]), atol=ATOL, err_msg=key
            )


def _pair(seed_or_batches, host_kwargs=None, dev_kwargs=None, n_updates=2, **dataset_kw):
    """Feed identical batches to host + device evaluators, return both computes."""
    host = MeanAveragePrecision(**(host_kwargs or {}))
    dev = MeanAveragePrecision(backend="device", num_classes=dataset_kw.get("n_cls", 6),
                               capacity=2048, **(dev_kwargs or {}))
    if isinstance(seed_or_batches, list):
        batches = seed_or_batches
    else:
        rng = np.random.default_rng(seed_or_batches)
        batches = [_rand_dataset(rng, **dataset_kw) for _ in range(n_updates)]
    for preds, target in batches:
        host.update(preds, target)
        dev.update(preds, target)
    return host.compute(), dev.compute(), dev


# ------------------------------------------------------------------ parity fuzz


_EXTRA = pytest.mark.slow  # extended fuzz seeds ride the scale tier, out of tier-1


@pytest.mark.parametrize("seed", (0, 1, 2, *(pytest.param(s, marks=_EXTRA) for s in (3, 4, 5))))
def test_device_parity_fuzz(seed):
    host_out, dev_out, _ = _pair(seed)
    _assert_parity(host_out, dev_out)


@pytest.mark.parametrize("seed", (0, 1, pytest.param(2, marks=_EXTRA)))
def test_device_parity_iscrowd_and_user_areas(seed):
    """Crowd gts (det-denominator IoU, ignored matches don't count) and
    user-provided areas overriding the box area for range assignment."""
    host_out, dev_out, _ = _pair(seed, crowd_rate=0.3, area_rate=0.5)
    _assert_parity(host_out, dev_out)


@pytest.mark.parametrize("seed", (3, pytest.param(4, marks=_EXTRA)))
def test_device_parity_degenerate_boxes(seed):
    """Zero/negative-extent boxes score zero IoU but still consume maxDet
    slots and count as FPs, exactly like the host path."""
    host_out, dev_out, _ = _pair(seed, degenerate_rate=0.4)
    _assert_parity(host_out, dev_out)


def test_device_parity_custom_maxdets():
    kw = {"max_detection_thresholds": [2, 5, 20]}
    host_out, dev_out, _ = _pair(7, host_kwargs=kw, dev_kwargs=kw, max_det=25)
    _assert_parity(host_out, dev_out)
    assert "mar_2" in dev_out and "mar_20" in dev_out


@pytest.mark.parametrize("seed", (6, pytest.param(5, marks=_EXTRA)))
def test_device_parity_class_metrics(seed):
    kw = {"class_metrics": True}
    host_out, dev_out, _ = _pair(seed, host_kwargs=kw, dev_kwargs=kw)
    _assert_parity(host_out, dev_out, class_metrics=True)


def test_device_parity_empty_preds_and_targets():
    """All-empty images on either side: npig==0 classes report -1 like the
    host evaluator; fully empty state returns the -1 sentinel dict."""
    rng = np.random.default_rng(11)
    preds, target = _rand_dataset(rng, n_imgs=8)
    no_dets = [{"boxes": np.zeros((0, 4), np.float32), "scores": np.zeros(0, np.float32),
                "labels": np.zeros(0, np.int32)} for _ in preds]
    no_gts = [{"boxes": np.zeros((0, 4), np.float32), "labels": np.zeros(0, np.int32)}
              for _ in target]
    host_out, dev_out, _ = _pair([(no_dets, target)])
    _assert_parity(host_out, dev_out)
    host_out, dev_out, _ = _pair([(preds, no_gts)])
    _assert_parity(host_out, dev_out)


def test_device_empty_compute_sentinel():
    dev = MeanAveragePrecision(backend="device")
    out = dev.compute()
    assert float(out["map"]) == -1.0 and float(out["mar_100"]) == -1.0
    assert np.asarray(out["classes"]).size == 0


def test_device_reset_then_reuse():
    host_out, dev_out, dev = _pair(13)
    dev.reset()
    assert dev._rows_used == {"det": 0, "gt": 0, "img": 0}
    rng = np.random.default_rng(14)
    preds, target = _rand_dataset(rng)
    host = MeanAveragePrecision()
    host.update(preds, target)
    dev.update(preds, target)
    _assert_parity(host.compute(), dev.compute())


# ----------------------------------------------------------- capacity sentinels


def test_device_capacity_overflow_raises():
    """Overflow raises BEFORE dispatch (the in-graph append would silently
    drop rows), and the state stays usable at its pre-overflow contents."""
    rng = np.random.default_rng(21)
    dev = DeviceMeanAveragePrecision(capacity=64, num_classes=6)
    preds, target = _rand_dataset(rng, n_imgs=4, empty_rate=0.0)
    dev.update(preds, target)
    big_preds, big_target = _rand_dataset(rng, n_imgs=40, empty_rate=0.0)
    with pytest.raises(TorchMetricsUserError, match="overflow"):
        dev.update(big_preds, big_target)
    out = dev.compute()  # pre-overflow rows still compute
    assert float(out["map"]) >= -1.0


def test_device_capacity_boundary_exact_fit():
    """A batch landing exactly on the capacity boundary is accepted; one more
    row overflows."""
    one_det = [{"boxes": np.asarray([[0.0, 0.0, 10.0, 10.0]], np.float32),
                "scores": np.asarray([0.9], np.float32), "labels": np.asarray([0], np.int32)}]
    one_gt = [{"boxes": np.asarray([[0.0, 0.0, 10.0, 10.0]], np.float32),
               "labels": np.asarray([0], np.int32)}]
    dev = DeviceMeanAveragePrecision(capacity=2, num_classes=2)
    dev.update(one_det, one_gt)
    dev.update(one_det, one_gt)  # det rows now exactly at capacity
    with pytest.raises(TorchMetricsUserError, match="overflow"):
        dev.update(one_det, one_gt)


def test_device_label_and_group_cap_validation():
    dev = DeviceMeanAveragePrecision(capacity=256, num_classes=3, gt_group_cap=2)
    bad_label = [{"boxes": np.asarray([[0.0, 0.0, 5.0, 5.0]], np.float32),
                  "scores": np.asarray([0.5], np.float32), "labels": np.asarray([3], np.int32)}]
    empty_gt = [{"boxes": np.zeros((0, 4), np.float32), "labels": np.zeros(0, np.int32)}]
    with pytest.raises(ValueError, match="num_classes"):
        dev.update(bad_label, empty_gt)
    empty_det = [{"boxes": np.zeros((0, 4), np.float32), "scores": np.zeros(0, np.float32),
                  "labels": np.zeros(0, np.int32)}]
    crowded = [{"boxes": np.tile(np.asarray([[0.0, 0.0, 5.0, 5.0]], np.float32), (3, 1)),
                "labels": np.zeros(3, np.int32)}]
    with pytest.raises(ValueError, match="gt_group_cap"):
        dev.update(empty_det, crowded)


def test_device_config_validation():
    with pytest.raises(ValueError, match="iou_type"):
        DeviceMeanAveragePrecision(iou_type="segm")
    with pytest.raises(ValueError, match="extended summary"):
        DeviceMeanAveragePrecision(extended_summary=True)
    with pytest.raises(ValueError, match="average"):
        DeviceMeanAveragePrecision(average="micro")
    with pytest.raises(ValueError, match="capacity"):
        DeviceMeanAveragePrecision(capacity=0)


def test_backend_keyword_routes_construction():
    dev = MeanAveragePrecision(backend="device", capacity=128)
    assert isinstance(dev, DeviceMeanAveragePrecision) and dev.capacity == 128
    host = MeanAveragePrecision()
    assert not isinstance(host, DeviceMeanAveragePrecision)


# ------------------------------------------------------------- AOT warm start


@pytest.mark.aot
def test_mapeval_precompile_and_warm_boot(tmp_path):
    """precompile writes the mapeval program; a fresh metric on a fresh plane
    over the same cache dir serves its first compute from a disk load."""
    cache = str(tmp_path / "aot")
    rng = np.random.default_rng(31)
    preds, target = _rand_dataset(rng)
    geometry = {"capacity": 512, "num_classes": 6}

    dev = DeviceMeanAveragePrecision(**geometry)
    report = dev.precompile(cache_dir=cache)
    assert report["mapeval"]["status"] == "written"

    aot.enable(cache)
    try:
        warm = DeviceMeanAveragePrecision(**geometry)
        warm.update(preds, target)
        out = warm.compute()
        slots = warm.__dict__.get("_aot_memo", {})
        sources = {k[0]: v.source for k, v in slots.items()}
        assert sources.get("mapeval") == "disk"
    finally:
        aot.disable()
    host = MeanAveragePrecision()
    host.update(preds, target)
    _assert_parity(host.compute(), out)
