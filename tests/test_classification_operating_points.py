"""Curve operating-point metrics: EER, LogAUC, {Precision,Recall,Sensitivity,
Specificity}@Fixed*, group fairness (reference tests/unittests/classification/)."""

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import precision_recall_curve as sk_pr_curve, roc_curve as sk_roc_curve

from conftest import seed_all
from torchmetrics_tpu.classification import (
    BinaryEER,
    BinaryFairness,
    BinaryGroupStatRates,
    BinaryLogAUC,
    BinaryPrecisionAtFixedRecall,
    BinaryRecallAtFixedPrecision,
    BinarySensitivityAtSpecificity,
    BinarySpecificityAtSensitivity,
    EER,
    LogAUC,
    MulticlassEER,
    MulticlassPrecisionAtFixedRecall,
    MulticlassRecallAtFixedPrecision,
    PrecisionAtFixedRecall,
    RecallAtFixedPrecision,
)
from torchmetrics_tpu.functional.classification import (
    binary_eer,
    binary_fairness,
    binary_groups_stat_rates,
    binary_logauc,
    binary_precision_at_fixed_recall,
    binary_recall_at_fixed_precision,
    binary_sensitivity_at_specificity,
    binary_specificity_at_sensitivity,
    demographic_parity,
    equal_opportunity,
    multiclass_eer,
    multiclass_recall_at_fixed_precision,
)

NUM_CLASSES = 5


def _sk_recall_at_fixed_precision(y, p, min_precision):
    precision, recall, thresholds = sk_pr_curve(y, p)
    best_r, best_t = 0.0, float("nan")
    best = None
    for pr, rc, th in zip(precision[:-1], recall[:-1], thresholds):
        if pr >= min_precision:
            cand = (rc, pr, th)
            if best is None or cand > best:
                best = cand
    # final curve point (recall 0, precision 1) has no threshold; reference zips to min len
    if best is not None:
        best_r, best_t = best[0], best[2]
    if best_r == 0.0:
        best_t = float("nan")
    return best_r, best_t


def _sk_eer(y, p):
    fpr, tpr, _ = sk_roc_curve(y, p, drop_intermediate=False)
    fnr = 1 - tpr
    i = np.argmin(np.abs(fpr - fnr))
    return (fpr[i] + fnr[i]) / 2


class TestRecallAtFixedPrecision:
    @pytest.mark.parametrize("min_precision", [0.3, 0.5, 0.8])
    def test_binary_unbinned_vs_sklearn(self, min_precision):
        rng = seed_all()
        p = rng.random(200).astype(np.float32)
        y = rng.integers(0, 2, 200)
        ref_r, ref_t = _sk_recall_at_fixed_precision(y, p, min_precision)
        r, t = binary_recall_at_fixed_precision(jnp.asarray(p), jnp.asarray(y), min_precision)
        np.testing.assert_allclose(float(r), ref_r, atol=1e-6)
        if not np.isnan(ref_t):
            np.testing.assert_allclose(float(t), ref_t, atol=1e-6)

    def test_binary_binned_close(self):
        rng = seed_all()
        p = rng.random(500).astype(np.float32)
        y = rng.integers(0, 2, 500)
        r_exact, _ = binary_recall_at_fixed_precision(jnp.asarray(p), jnp.asarray(y), 0.5)
        r_binned, _ = binary_recall_at_fixed_precision(jnp.asarray(p), jnp.asarray(y), 0.5, thresholds=200)
        np.testing.assert_allclose(float(r_binned), float(r_exact), atol=0.05)

    def test_class_accumulation(self):
        rng = seed_all()
        metric = BinaryRecallAtFixedPrecision(min_precision=0.5)
        chunks = [(rng.random(64).astype(np.float32), rng.integers(0, 2, 64)) for _ in range(4)]
        for p, y in chunks:
            metric.update(jnp.asarray(p), jnp.asarray(y))
        p_all = np.concatenate([c[0] for c in chunks])
        y_all = np.concatenate([c[1] for c in chunks])
        ref_r, _ = _sk_recall_at_fixed_precision(y_all, p_all, 0.5)
        r, t = metric.compute()
        np.testing.assert_allclose(float(r), ref_r, atol=1e-6)

    def test_multiclass_shapes(self):
        rng = seed_all()
        p = rng.random((100, NUM_CLASSES)).astype(np.float32)
        p = p / p.sum(-1, keepdims=True)
        y = rng.integers(0, NUM_CLASSES, 100)
        r, t = multiclass_recall_at_fixed_precision(jnp.asarray(p), jnp.asarray(y), NUM_CLASSES, 0.5)
        assert r.shape == (NUM_CLASSES,)
        assert t.shape == (NUM_CLASSES,)
        # per-class parity vs binary sklearn one-vs-rest
        for c in range(NUM_CLASSES):
            ref_r, _ = _sk_recall_at_fixed_precision((y == c).astype(int), p[:, c], 0.5)
            np.testing.assert_allclose(float(r[c]), ref_r, atol=1e-6, err_msg=f"class {c}")

    def test_facade(self):
        m = RecallAtFixedPrecision(task="binary", min_precision=0.5)
        assert isinstance(m, BinaryRecallAtFixedPrecision)
        m = RecallAtFixedPrecision(task="multiclass", min_precision=0.5, num_classes=3)
        assert isinstance(m, MulticlassRecallAtFixedPrecision)


class TestPrecisionAtFixedRecall:
    @pytest.mark.parametrize("min_recall", [0.3, 0.5, 0.8])
    def test_binary_vs_sklearn(self, min_recall):
        rng = seed_all()
        p = rng.random(200).astype(np.float32)
        y = rng.integers(0, 2, 200)
        precision, recall, thresholds = sk_pr_curve(y, p)
        best = max(
            ((pr, rc, th) for pr, rc, th in zip(precision[:-1], recall[:-1], thresholds) if rc >= min_recall),
            default=None,
        )
        ref_p = best[0] if best else 0.0
        p_val, t_val = binary_precision_at_fixed_recall(jnp.asarray(p), jnp.asarray(y), min_recall)
        np.testing.assert_allclose(float(p_val), ref_p, atol=1e-6)

    def test_class_and_facade(self):
        m = PrecisionAtFixedRecall(task="binary", min_recall=0.5)
        assert isinstance(m, BinaryPrecisionAtFixedRecall)
        rng = seed_all()
        p = rng.random(128).astype(np.float32)
        y = rng.integers(0, 2, 128)
        m.update(jnp.asarray(p), jnp.asarray(y))
        val, thr = m.compute()
        fn_val, fn_thr = binary_precision_at_fixed_recall(jnp.asarray(p), jnp.asarray(y), 0.5)
        np.testing.assert_allclose(float(val), float(fn_val), atol=1e-6)


class TestSensitivitySpecificityAt:
    def test_sensitivity_at_specificity_vs_roc(self):
        rng = seed_all()
        p = rng.random(300).astype(np.float32)
        y = rng.integers(0, 2, 300)
        min_spec = 0.6
        fpr, tpr, thr = sk_roc_curve(y, p)
        mask = (1 - fpr) >= min_spec
        ref = tpr[mask].max() if mask.any() else 0.0
        sens, t = binary_sensitivity_at_specificity(jnp.asarray(p), jnp.asarray(y), min_spec)
        np.testing.assert_allclose(float(sens), ref, atol=1e-6)

    def test_specificity_at_sensitivity_vs_roc(self):
        rng = seed_all()
        p = rng.random(300).astype(np.float32)
        y = rng.integers(0, 2, 300)
        min_sens = 0.6
        fpr, tpr, thr = sk_roc_curve(y, p)
        mask = tpr >= min_sens
        ref = (1 - fpr)[mask].max() if mask.any() else 0.0
        spec, t = binary_specificity_at_sensitivity(jnp.asarray(p), jnp.asarray(y), min_sens)
        np.testing.assert_allclose(float(spec), ref, atol=1e-6)

    def test_class_stateful(self):
        rng = seed_all()
        m = BinarySensitivityAtSpecificity(min_specificity=0.5)
        p = rng.random(128).astype(np.float32)
        y = rng.integers(0, 2, 128)
        m.update(jnp.asarray(p), jnp.asarray(y))
        v1, t1 = m.compute()
        v2, t2 = binary_sensitivity_at_specificity(jnp.asarray(p), jnp.asarray(y), 0.5)
        np.testing.assert_allclose(float(v1), float(v2), atol=1e-6)
        m2 = BinarySpecificityAtSensitivity(min_sensitivity=0.5)
        m2.update(jnp.asarray(p), jnp.asarray(y))
        w1, _ = m2.compute()
        w2, _ = binary_specificity_at_sensitivity(jnp.asarray(p), jnp.asarray(y), 0.5)
        np.testing.assert_allclose(float(w1), float(w2), atol=1e-6)


class TestEER:
    def test_binary_vs_sklearn_roc(self):
        rng = seed_all()
        p = rng.random(300).astype(np.float32)
        y = rng.integers(0, 2, 300)
        np.testing.assert_allclose(float(binary_eer(jnp.asarray(p), jnp.asarray(y))), _sk_eer(y, p), atol=1e-6)

    def test_multiclass(self):
        rng = seed_all()
        p = rng.random((200, NUM_CLASSES)).astype(np.float32)
        p = p / p.sum(-1, keepdims=True)
        y = rng.integers(0, NUM_CLASSES, 200)
        out = multiclass_eer(jnp.asarray(p), jnp.asarray(y), NUM_CLASSES)
        assert out.shape == (NUM_CLASSES,)
        for c in range(NUM_CLASSES):
            np.testing.assert_allclose(float(out[c]), _sk_eer((y == c).astype(int), p[:, c]), atol=1e-6)

    def test_class_and_facade(self):
        rng = seed_all()
        m = EER(task="binary")
        assert isinstance(m, BinaryEER)
        p = rng.random(128).astype(np.float32)
        y = rng.integers(0, 2, 128)
        m.update(jnp.asarray(p), jnp.asarray(y))
        np.testing.assert_allclose(float(m.compute()), _sk_eer(y, p), atol=1e-6)
        assert isinstance(EER(task="multiclass", num_classes=3), MulticlassEER)

    def test_binned_close_to_exact(self):
        rng = seed_all()
        p = rng.random(1000).astype(np.float32)
        y = rng.integers(0, 2, 1000)
        exact = float(binary_eer(jnp.asarray(p), jnp.asarray(y)))
        binned = float(binary_eer(jnp.asarray(p), jnp.asarray(y), thresholds=200))
        np.testing.assert_allclose(binned, exact, atol=0.02)


class TestLogAUC:
    def test_binary_range_properties(self):
        rng = seed_all()
        # strong classifier: logauc should be high; random: lower
        y = rng.integers(0, 2, 2000)
        strong = np.clip(y + rng.normal(0, 0.2, 2000), 0, 1).astype(np.float32)
        v_strong = float(binary_logauc(jnp.asarray(strong), jnp.asarray(y), fpr_range=(0.01, 1.0)))
        v_rand = float(binary_logauc(jnp.asarray(rng.random(2000).astype(np.float32)), jnp.asarray(y), fpr_range=(0.01, 1.0)))
        assert 0.0 <= v_rand <= 1.0
        assert v_strong > v_rand

    def test_perfect_classifier_is_one(self):
        y = np.concatenate([np.zeros(500, int), np.ones(500, int)])
        p = np.concatenate([np.linspace(0, 0.4, 500), np.linspace(0.6, 1.0, 500)]).astype(np.float32)
        v = float(binary_logauc(jnp.asarray(p), jnp.asarray(y), fpr_range=(0.001, 0.1)))
        np.testing.assert_allclose(v, 1.0, atol=1e-5)

    def test_class_and_facade(self):
        rng = seed_all()
        m = LogAUC(task="binary")
        assert isinstance(m, BinaryLogAUC)
        p = rng.random(256).astype(np.float32)
        y = rng.integers(0, 2, 256)
        m.update(jnp.asarray(p), jnp.asarray(y))
        np.testing.assert_allclose(
            float(m.compute()), float(binary_logauc(jnp.asarray(p), jnp.asarray(y))), atol=1e-6
        )

    def test_bad_range_raises(self):
        with pytest.raises(ValueError):
            binary_logauc(jnp.asarray([0.5]), jnp.asarray([1]), fpr_range=(0.5, 0.1))


class TestGroupFairness:
    def test_stat_rates(self):
        preds = jnp.asarray([1, 0, 1, 1, 0, 1], dtype=jnp.int32)
        target = jnp.asarray([1, 0, 0, 1, 1, 1])
        groups = jnp.asarray([0, 0, 0, 1, 1, 1])
        out = binary_groups_stat_rates(preds, target, groups, num_groups=2)
        # group 0: tp=1 fp=1 tn=1 fn=0 → /3
        np.testing.assert_allclose(np.asarray(out["group_0"]), [1 / 3, 1 / 3, 1 / 3, 0.0], atol=1e-6)
        # group 1: tp=2 fp=0 tn=0 fn=1 → /3
        np.testing.assert_allclose(np.asarray(out["group_1"]), [2 / 3, 0.0, 0.0, 1 / 3], atol=1e-6)

    def test_demographic_parity(self):
        rng = seed_all()
        preds = jnp.asarray(rng.random(400).astype(np.float32))
        groups = jnp.asarray(rng.integers(0, 2, 400))
        out = demographic_parity(preds, groups)
        key = next(iter(out))
        assert key.startswith("DP_")
        p, g = np.asarray(preds) > 0.5, np.asarray(groups)
        rates = np.asarray([p[g == i].mean() for i in range(2)])
        np.testing.assert_allclose(float(out[key]), rates.min() / rates.max(), atol=1e-6)

    def test_equal_opportunity(self):
        rng = seed_all()
        preds = jnp.asarray(rng.random(400).astype(np.float32))
        target = jnp.asarray(rng.integers(0, 2, 400))
        groups = jnp.asarray(rng.integers(0, 2, 400))
        out = equal_opportunity(preds, target, groups)
        key = next(iter(out))
        assert key.startswith("EO_")
        p, t, g = np.asarray(preds) > 0.5, np.asarray(target), np.asarray(groups)
        tprs = np.asarray([(p & (t == 1) & (g == i)).sum() / ((t == 1) & (g == i)).sum() for i in range(2)])
        np.testing.assert_allclose(float(out[key]), tprs.min() / tprs.max(), atol=1e-6)

    def test_binary_fairness_all_and_class(self):
        rng = seed_all()
        preds = jnp.asarray(rng.random(256).astype(np.float32))
        target = jnp.asarray(rng.integers(0, 2, 256))
        groups = jnp.asarray(rng.integers(0, 2, 256))
        fn_out = binary_fairness(preds, target, groups, task="all")
        assert len(fn_out) == 2
        m = BinaryFairness(num_groups=2, task="all")
        m.update(preds, target, groups)
        cls_out = m.compute()
        for k in fn_out:
            np.testing.assert_allclose(float(cls_out[k]), float(fn_out[k]), atol=1e-6)

    def test_group_stat_rates_class_accumulates(self):
        rng = seed_all()
        m = BinaryGroupStatRates(num_groups=3)
        all_p, all_t, all_g = [], [], []
        for _ in range(3):
            p = rng.random(64).astype(np.float32)
            t = rng.integers(0, 2, 64)
            g = rng.integers(0, 3, 64)
            m.update(jnp.asarray(p), jnp.asarray(t), jnp.asarray(g))
            all_p.append(p), all_t.append(t), all_g.append(g)
        out = m.compute()
        ref = binary_groups_stat_rates(
            jnp.asarray(np.concatenate(all_p)), jnp.asarray(np.concatenate(all_t)),
            jnp.asarray(np.concatenate(all_g)), num_groups=3,
        )
        for k in ref:
            np.testing.assert_allclose(np.asarray(out[k]), np.asarray(ref[k]), atol=1e-6)


def test_operating_point_task_facades_dispatch():
    """The four facade wrappers must dispatch to the matching task kernel."""
    import numpy as np
    import jax.numpy as jnp
    import pytest as _pytest

    import torchmetrics_tpu.functional as F

    rng = np.random.default_rng(0)
    p_bin = jnp.asarray(rng.random(64).astype(np.float32))
    t_bin = jnp.asarray((rng.random(64) > 0.5).astype(np.int32))
    p_mc = jnp.asarray(rng.dirichlet(np.ones(4), 64).astype(np.float32))
    t_mc = jnp.asarray(rng.integers(0, 4, 64).astype(np.int32))

    cases = [
        (F.precision_at_fixed_recall, F.binary_precision_at_fixed_recall,
         F.multiclass_precision_at_fixed_recall, "min_recall"),
        (F.recall_at_fixed_precision, F.binary_recall_at_fixed_precision,
         F.multiclass_recall_at_fixed_precision, "min_precision"),
        (F.sensitivity_at_specificity, F.binary_sensitivity_at_specificity,
         F.multiclass_sensitivity_at_specificity, "min_specificity"),
        (F.specificity_at_sensitivity, F.binary_specificity_at_sensitivity,
         F.multiclass_specificity_at_sensitivity, "min_sensitivity"),
    ]
    for facade, binary_fn, multiclass_fn, floor_name in cases:
        got = facade(p_bin, t_bin, task="binary", **{floor_name: 0.5}, thresholds=50)
        want = binary_fn(p_bin, t_bin, 0.5, thresholds=50)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-7)
        got_mc = facade(p_mc, t_mc, task="multiclass", num_classes=4, **{floor_name: 0.5}, thresholds=50)
        want_mc = multiclass_fn(p_mc, t_mc, 4, 0.5, thresholds=50)
        for g, w in zip(got_mc, want_mc):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-7)
        with _pytest.raises(ValueError, match="num_classes"):
            facade(p_mc, t_mc, task="multiclass", **{floor_name: 0.5})
