"""Universal metric-class invariants, swept across the whole tower surface.

The reference's ``_class_test`` (testers.py:142-324) checks a set of structural
invariants for every metric; round-2 coverage sampled them per-domain. This
battery runs the full set through one registry of
(constructor, batch generator) cases (~140 classes):

1. ``compute`` is idempotent (two calls, same value) and matches update+compute
   replayed on a fresh instance,
2. ``clone()`` is independent (updating the clone does not disturb the parent),
3. pickling mid-accumulation preserves state,
4. ``merge_state`` over two shards equals one-shot accumulation,
5. ``reset()`` restores defaults (fresh compute on batch 0 matches a new metric),
6. ``state_dict``/``load_state_dict`` round-trips persistent state.

Model-backed metrics (weights/external artifacts) and wrappers (covered by their
own test files) are out of scope here.
"""

from __future__ import annotations

import pickle

import jax.numpy as jnp
import numpy as np
import pytest

import torchmetrics_tpu as tm
from tests.helpers import _assert_allclose

_RNG = np.random.default_rng(77)
N, C, L = 24, 5, 4


def _j(x):
    return jnp.asarray(x)


# ---- input generators (one fresh batch per call) --------------------------------

def binary():
    return _j(_RNG.random(N, dtype=np.float32)), _j(_RNG.integers(0, 2, N).astype(np.int32))


def multiclass():
    return (
        _j(_RNG.normal(size=(N, C)).astype(np.float32)),
        _j(_RNG.integers(0, C, N).astype(np.int32)),
    )


def multilabel():
    return (
        _j(_RNG.random((N, L), dtype=np.float32)),
        _j(_RNG.integers(0, 2, (N, L)).astype(np.int32)),
    )


def reg():
    return _j(_RNG.random(N, dtype=np.float32)), _j(_RNG.random(N, dtype=np.float32) + 0.1)


def reg_pos():
    return _j(_RNG.random(N, dtype=np.float32) + 0.5), _j(_RNG.random(N, dtype=np.float32) + 0.5)


def dist():  # probability rows
    p = _RNG.random((N, C), dtype=np.float32) + 0.05
    q = _RNG.random((N, C), dtype=np.float32) + 0.05
    return _j(p / p.sum(1, keepdims=True)), _j(q / q.sum(1, keepdims=True))


def audio():
    return (
        _j(_RNG.normal(size=(4, 256)).astype(np.float32)),
        _j(_RNG.normal(size=(4, 256)).astype(np.float32)),
    )


def image():
    return (
        _j(_RNG.random((2, 3, 16, 16), dtype=np.float32)),
        _j(_RNG.random((2, 3, 16, 16), dtype=np.float32)),
    )


def image_big():
    return (
        _j(_RNG.random((2, 3, 48, 48), dtype=np.float32)),
        _j(_RNG.random((2, 3, 48, 48), dtype=np.float32)),
    )


def labels_pair():
    return _j(_RNG.integers(0, 4, N).astype(np.int32)), _j(_RNG.integers(0, 4, N).astype(np.int32))


def intrinsic():
    return _j(_RNG.normal(size=(N, 3)).astype(np.float32)), _j(_RNG.integers(0, 3, N).astype(np.int32))


def retrieval():
    return (
        _j(_RNG.random(N, dtype=np.float32)),
        _j(_RNG.integers(0, 2, N).astype(np.int32)),
        _j(np.sort(_RNG.integers(0, 4, N)).astype(np.int32)),
    )


def texts():
    vocab = ["alpha", "beta", "gamma", "delta", "epsilon"]
    preds = [" ".join(_RNG.choice(vocab, 5)) for _ in range(4)]
    target = [[" ".join(_RNG.choice(vocab, 5))] for _ in range(4)]
    return preds, target


def texts_flat():
    preds, target = texts()
    return preds, [t[0] for t in target]


def perplexity():
    return (
        _j(_RNG.normal(size=(4, 6, C)).astype(np.float32)),
        _j(_RNG.integers(0, C, (4, 6)).astype(np.int32)),
    )


def segmentation():
    return (
        _j(_RNG.integers(0, 3, (2, 3, 8, 8)).astype(np.int32)),
        _j(_RNG.integers(0, 2, (2, 3, 8, 8)).astype(np.int32)),
    )


def seg_labels():
    return (
        _j(_RNG.integers(0, 3, (2, 8, 8)).astype(np.int32)),
        _j(_RNG.integers(0, 3, (2, 8, 8)).astype(np.int32)),
    )


def boxes():
    def make(n):
        xy = _RNG.uniform(0, 50, (n, 2))
        wh = _RNG.uniform(5, 30, (n, 2))
        return np.concatenate([xy, xy + wh], -1).astype(np.float32)

    preds = [{"boxes": _j(make(3)), "scores": _j(_RNG.random(3, dtype=np.float32)),
              "labels": _j(_RNG.integers(0, 2, 3).astype(np.int32))}]
    target = [{"boxes": _j(make(2)), "labels": _j(_RNG.integers(0, 2, 2).astype(np.int32))}]
    return preds, target


def agg_value():
    return (_j(_RNG.random(N, dtype=np.float32)),)


def procrustes():
    return (
        _j(_RNG.normal(size=(2, 10, 3)).astype(np.float32)),
        _j(_RNG.normal(size=(2, 10, 3)).astype(np.float32)),
    )


# ---- the registry ----------------------------------------------------------------

CASES = {
    # classification: binary
    "BinaryAccuracy": (lambda: tm.BinaryAccuracy(), binary),
    "BinaryPrecision": (lambda: tm.BinaryPrecision(), binary),
    "BinaryRecall": (lambda: tm.BinaryRecall(), binary),
    "BinaryF1Score": (lambda: tm.BinaryF1Score(), binary),
    "BinaryFBetaScore": (lambda: tm.BinaryFBetaScore(beta=0.5), binary),
    "BinarySpecificity": (lambda: tm.BinarySpecificity(), binary),
    "BinaryStatScores": (lambda: tm.BinaryStatScores(), binary),
    "BinaryHammingDistance": (lambda: tm.BinaryHammingDistance(), binary),
    "BinaryNegativePredictiveValue": (lambda: tm.BinaryNegativePredictiveValue(), binary),
    "BinaryCohenKappa": (lambda: tm.BinaryCohenKappa(), binary),
    "BinaryMatthewsCorrCoef": (lambda: tm.BinaryMatthewsCorrCoef(), binary),
    "BinaryJaccardIndex": (lambda: tm.BinaryJaccardIndex(), binary),
    "BinaryConfusionMatrix": (lambda: tm.BinaryConfusionMatrix(), binary),
    "BinaryAUROC": (lambda: tm.BinaryAUROC(thresholds=16), binary),
    "BinaryAveragePrecision": (lambda: tm.BinaryAveragePrecision(thresholds=16), binary),
    "BinaryROC": (lambda: tm.BinaryROC(thresholds=16), binary),
    "BinaryPrecisionRecallCurve": (lambda: tm.BinaryPrecisionRecallCurve(thresholds=16), binary),
    "BinaryCalibrationError": (lambda: tm.BinaryCalibrationError(), binary),
    "BinaryEER": (lambda: tm.BinaryEER(thresholds=16), binary),
    "BinaryLogAUC": (lambda: tm.BinaryLogAUC(thresholds=16), binary),
    "BinaryHingeLoss": (lambda: tm.BinaryHingeLoss(), binary),
    # classification: multiclass
    "MulticlassAccuracy": (lambda: tm.MulticlassAccuracy(C), multiclass),
    "MulticlassPrecision": (lambda: tm.MulticlassPrecision(C), multiclass),
    "MulticlassRecall": (lambda: tm.MulticlassRecall(C), multiclass),
    "MulticlassF1Score": (lambda: tm.MulticlassF1Score(C), multiclass),
    "MulticlassSpecificity": (lambda: tm.MulticlassSpecificity(C), multiclass),
    "MulticlassStatScores": (lambda: tm.MulticlassStatScores(C), multiclass),
    "MulticlassConfusionMatrix": (lambda: tm.MulticlassConfusionMatrix(C), multiclass),
    "MulticlassCohenKappa": (lambda: tm.MulticlassCohenKappa(C), multiclass),
    "MulticlassMatthewsCorrCoef": (lambda: tm.MulticlassMatthewsCorrCoef(C), multiclass),
    "MulticlassJaccardIndex": (lambda: tm.MulticlassJaccardIndex(C), multiclass),
    "MulticlassAUROC": (lambda: tm.MulticlassAUROC(C, thresholds=16), multiclass),
    "MulticlassAveragePrecision": (lambda: tm.MulticlassAveragePrecision(C, thresholds=16), multiclass),
    "MulticlassROC": (lambda: tm.MulticlassROC(C, thresholds=16), multiclass),
    "MulticlassCalibrationError": (lambda: tm.MulticlassCalibrationError(C), multiclass),
    "MulticlassExactMatch": (lambda: tm.MulticlassExactMatch(C), multiclass),
    "MulticlassHingeLoss": (lambda: tm.MulticlassHingeLoss(C), multiclass),
    # classification: multilabel
    "MultilabelAccuracy": (lambda: tm.MultilabelAccuracy(L), multilabel),
    "MultilabelF1Score": (lambda: tm.MultilabelF1Score(L), multilabel),
    "MultilabelConfusionMatrix": (lambda: tm.MultilabelConfusionMatrix(L), multilabel),
    "MultilabelAUROC": (lambda: tm.MultilabelAUROC(L, thresholds=16), multilabel),
    "MultilabelExactMatch": (lambda: tm.MultilabelExactMatch(L), multilabel),
    "MultilabelRankingAveragePrecision": (lambda: tm.MultilabelRankingAveragePrecision(L), multilabel),
    "MultilabelRankingLoss": (lambda: tm.MultilabelRankingLoss(L), multilabel),
    "MultilabelCoverageError": (lambda: tm.MultilabelCoverageError(L), multilabel),
    # regression
    "MeanSquaredError": (lambda: tm.MeanSquaredError(), reg),
    "MeanAbsoluteError": (lambda: tm.MeanAbsoluteError(), reg),
    "MeanSquaredLogError": (lambda: tm.MeanSquaredLogError(), reg_pos),
    "MeanAbsolutePercentageError": (lambda: tm.MeanAbsolutePercentageError(), reg_pos),
    "SymmetricMeanAbsolutePercentageError": (lambda: tm.SymmetricMeanAbsolutePercentageError(), reg_pos),
    "WeightedMeanAbsolutePercentageError": (lambda: tm.WeightedMeanAbsolutePercentageError(), reg_pos),
    "ExplainedVariance": (lambda: tm.ExplainedVariance(), reg),
    "R2Score": (lambda: tm.R2Score(), reg),
    "PearsonCorrCoef": (lambda: tm.PearsonCorrCoef(), reg),
    "SpearmanCorrCoef": (lambda: tm.SpearmanCorrCoef(), reg),
    "KendallRankCorrCoef": (lambda: tm.KendallRankCorrCoef(), reg),
    "ConcordanceCorrCoef": (lambda: tm.ConcordanceCorrCoef(), reg),
    "CosineSimilarity": (lambda: tm.CosineSimilarity(), lambda: (
        _j(_RNG.random((N, 3), dtype=np.float32)), _j(_RNG.random((N, 3), dtype=np.float32)))),
    "MinkowskiDistance": (lambda: tm.MinkowskiDistance(p=3), reg),
    "LogCoshError": (lambda: tm.LogCoshError(), reg),
    "TweedieDevianceScore": (lambda: tm.TweedieDevianceScore(), reg_pos),
    "RelativeSquaredError": (lambda: tm.RelativeSquaredError(), reg),
    "NormalizedRootMeanSquaredError": (lambda: tm.NormalizedRootMeanSquaredError(), reg),
    "CriticalSuccessIndex": (lambda: tm.CriticalSuccessIndex(0.5), reg),
    "KLDivergence": (lambda: tm.KLDivergence(), dist),
    "JensenShannonDivergence": (lambda: tm.JensenShannonDivergence(), dist),
    "ContinuousRankedProbabilityScore": (lambda: tm.ContinuousRankedProbabilityScore(), lambda: (
        _j(_RNG.normal(size=(N, 6)).astype(np.float32)), _j(_RNG.normal(size=N).astype(np.float32)))),
    # aggregation
    "MeanMetric": (lambda: tm.MeanMetric(), agg_value),
    "SumMetric": (lambda: tm.SumMetric(), agg_value),
    "MaxMetric": (lambda: tm.MaxMetric(), agg_value),
    "MinMetric": (lambda: tm.MinMetric(), agg_value),
    "CatMetric": (lambda: tm.CatMetric(), agg_value),
    # audio
    "SignalNoiseRatio": (lambda: tm.SignalNoiseRatio(), audio),
    "ScaleInvariantSignalNoiseRatio": (lambda: tm.ScaleInvariantSignalNoiseRatio(), audio),
    "ScaleInvariantSignalDistortionRatio": (lambda: tm.ScaleInvariantSignalDistortionRatio(), audio),
    "SourceAggregatedSignalDistortionRatio": (lambda: tm.SourceAggregatedSignalDistortionRatio(), lambda: (
        _j(_RNG.normal(size=(2, 3, 128)).astype(np.float32)), _j(_RNG.normal(size=(2, 3, 128)).astype(np.float32)))),
    "SignalDistortionRatio": (lambda: tm.SignalDistortionRatio(filter_length=16), audio),
    "SpeechReverberationModulationEnergyRatio": (
        lambda: tm.SpeechReverberationModulationEnergyRatio(8000),
        lambda: (_j(_RNG.normal(size=(1, 4000)).astype(np.float32)),),
    ),
    # image (tensor-math)
    "PeakSignalNoiseRatio": (lambda: tm.PeakSignalNoiseRatio(data_range=1.0), image),
    "StructuralSimilarityIndexMeasure": (lambda: tm.StructuralSimilarityIndexMeasure(data_range=1.0), image_big),
    "MultiScaleStructuralSimilarityIndexMeasure": (
        lambda: tm.MultiScaleStructuralSimilarityIndexMeasure(data_range=1.0), lambda: (
            _j(_RNG.random((2, 3, 180, 180), dtype=np.float32)),
            _j(_RNG.random((2, 3, 180, 180), dtype=np.float32)))),
    "UniversalImageQualityIndex": (lambda: tm.UniversalImageQualityIndex(), image_big),
    "TotalVariation": (lambda: tm.TotalVariation(), lambda: (image()[0],)),
    "SpectralAngleMapper": (lambda: tm.SpectralAngleMapper(), image),
    "SpatialCorrelationCoefficient": (lambda: tm.SpatialCorrelationCoefficient(), image_big),
    "ErrorRelativeGlobalDimensionlessSynthesis": (
        lambda: tm.ErrorRelativeGlobalDimensionlessSynthesis(), image),
    "RelativeAverageSpectralError": (lambda: tm.RelativeAverageSpectralError(), image_big),
    "RootMeanSquaredErrorUsingSlidingWindow": (
        lambda: tm.RootMeanSquaredErrorUsingSlidingWindow(), image_big),
    "VisualInformationFidelity": (lambda: tm.VisualInformationFidelity(), lambda: (
        _j(_RNG.random((2, 3, 48, 48), dtype=np.float32)), _j(_RNG.random((2, 3, 48, 48), dtype=np.float32)))),
    "PeakSignalNoiseRatioWithBlockedEffect": (
        lambda: tm.PeakSignalNoiseRatioWithBlockedEffect(data_range=1.0), lambda: (
            _j(_RNG.random((2, 1, 16, 16), dtype=np.float32)),
            _j(_RNG.random((2, 1, 16, 16), dtype=np.float32)))),
    # text (host string metrics)
    "BLEUScore": (lambda: tm.BLEUScore(), texts),
    "SacreBLEUScore": (lambda: tm.SacreBLEUScore(), texts),
    "CharErrorRate": (lambda: tm.CharErrorRate(), texts_flat),
    "WordErrorRate": (lambda: tm.WordErrorRate(), texts_flat),
    "MatchErrorRate": (lambda: tm.MatchErrorRate(), texts_flat),
    "WordInfoLost": (lambda: tm.WordInfoLost(), texts_flat),
    "WordInfoPreserved": (lambda: tm.WordInfoPreserved(), texts_flat),
    "EditDistance": (lambda: tm.EditDistance(), texts_flat),
    "ExtendedEditDistance": (lambda: tm.ExtendedEditDistance(), texts_flat),
    "CHRFScore": (lambda: tm.CHRFScore(), texts),
    "TranslationEditRate": (lambda: tm.TranslationEditRate(), texts),
    "Perplexity": (lambda: tm.Perplexity(), perplexity),
    # retrieval
    "RetrievalMAP": (lambda: tm.RetrievalMAP(), retrieval),
    "RetrievalMRR": (lambda: tm.RetrievalMRR(), retrieval),
    "RetrievalPrecision": (lambda: tm.RetrievalPrecision(), retrieval),
    "RetrievalRecall": (lambda: tm.RetrievalRecall(), retrieval),
    "RetrievalHitRate": (lambda: tm.RetrievalHitRate(), retrieval),
    "RetrievalFallOut": (lambda: tm.RetrievalFallOut(), retrieval),
    "RetrievalNormalizedDCG": (lambda: tm.RetrievalNormalizedDCG(), retrieval),
    "RetrievalRPrecision": (lambda: tm.RetrievalRPrecision(), retrieval),
    "RetrievalAUROC": (lambda: tm.RetrievalAUROC(), retrieval),
    # clustering
    "MutualInfoScore": (lambda: tm.MutualInfoScore(), labels_pair),
    "AdjustedMutualInfoScore": (lambda: tm.AdjustedMutualInfoScore(), labels_pair),
    "NormalizedMutualInfoScore": (lambda: tm.NormalizedMutualInfoScore(), labels_pair),
    "RandScore": (lambda: tm.RandScore(), labels_pair),
    "AdjustedRandScore": (lambda: tm.AdjustedRandScore(), labels_pair),
    "FowlkesMallowsIndex": (lambda: tm.FowlkesMallowsIndex(), labels_pair),
    "HomogeneityScore": (lambda: tm.HomogeneityScore(), labels_pair),
    "CompletenessScore": (lambda: tm.CompletenessScore(), labels_pair),
    "VMeasureScore": (lambda: tm.VMeasureScore(), labels_pair),
    "CalinskiHarabaszScore": (lambda: tm.CalinskiHarabaszScore(), intrinsic),
    "DaviesBouldinScore": (lambda: tm.DaviesBouldinScore(), intrinsic),
    "DunnIndex": (lambda: tm.DunnIndex(), intrinsic),
    "ClusterAccuracy": (lambda: tm.ClusterAccuracy(num_classes=4), labels_pair),
    # nominal
    "CramersV": (lambda: tm.CramersV(num_classes=4), labels_pair),
    "PearsonsContingencyCoefficient": (lambda: tm.PearsonsContingencyCoefficient(num_classes=4), labels_pair),
    "TheilsU": (lambda: tm.TheilsU(num_classes=4), labels_pair),
    "TschuprowsT": (lambda: tm.TschuprowsT(num_classes=4), labels_pair),
    "FleissKappa": (lambda: tm.FleissKappa(mode="counts"), lambda: (
        _j(_RNG.integers(0, 5, (8, 3)).astype(np.int32)),)),
    # segmentation
    "DiceScore": (lambda: tm.DiceScore(num_classes=3), segmentation),
    "GeneralizedDiceScore": (lambda: tm.GeneralizedDiceScore(num_classes=3), segmentation),
    "MeanIoU": (lambda: tm.MeanIoU(num_classes=3), segmentation),
    "HausdorffDistance": (lambda: tm.HausdorffDistance(num_classes=3), seg_labels),
    # detection
    "IntersectionOverUnion": (lambda: tm.IntersectionOverUnion(), boxes),
    "GeneralizedIntersectionOverUnion": (lambda: tm.GeneralizedIntersectionOverUnion(), boxes),
    "DistanceIntersectionOverUnion": (lambda: tm.DistanceIntersectionOverUnion(), boxes),
    "CompleteIntersectionOverUnion": (lambda: tm.CompleteIntersectionOverUnion(), boxes),
    "MeanAveragePrecision": (lambda: tm.MeanAveragePrecision(), boxes),
    # shape
    "ProcrustesDisparity": (lambda: tm.ProcrustesDisparity(), procrustes),
}

# merge_state == one-shot does not hold where compute is order/subset dependent
_SKIP_MERGE = {
    "SpeechReverberationModulationEnergyRatio",  # single-update generator (one shard empty)
}

# forward's batch-value contract cannot hold where a metric's value is not
# defined on a single batch; pin exceptions BY NAME (empty until proven needed)
_SKIP_FORWARD: set = set()


@pytest.fixture(scope="module")
def batches():
    out = {}
    import zlib

    for name, (_, gen) in CASES.items():
        # crc32, not hash(): PYTHONHASHSEED-salted hashes would make every CI run
        # test different data, so failures could never be reproduced
        rng_state = np.random.default_rng(zlib.crc32(name.encode()))
        global _RNG
        keep = _RNG
        _RNG = rng_state
        out[name] = [gen() for _ in range(3)]
        _RNG = keep
    return out


@pytest.mark.parametrize("name", list(CASES), ids=list(CASES))
def test_universal_invariants(name, batches):
    ctor, _ = CASES[name]
    data = batches[name]

    # 1) update+compute, idempotence
    metric = ctor()
    for batch in data:
        metric.update(*batch)
    first = metric.compute()
    again = metric.compute()
    _assert_allclose(again, first, atol=0, msg=f"{name}: compute not idempotent")

    # 2) clone independence (clone made mid-stream diverges without disturbing parent)
    metric2 = ctor()
    metric2.update(*data[0])
    clone = metric2.clone()
    clone.update(*data[1])
    metric2_val = metric2.compute()
    fresh = ctor()
    fresh.update(*data[0])
    _assert_allclose(metric2_val, fresh.compute(), msg=f"{name}: clone update leaked into parent")

    # 3) pickle mid-accumulation
    metric3 = ctor()
    metric3.update(*data[0])
    metric3 = pickle.loads(pickle.dumps(metric3))
    for batch in data[1:]:
        metric3.update(*batch)
    _assert_allclose(metric3.compute(), first, msg=f"{name}: pickle round-trip changed state")

    # 4) merge_state over shards == one-shot
    if name not in _SKIP_MERGE:
        a, b = ctor(), ctor()
        a.update(*data[0])
        b.update(*data[1])
        b.update(*data[2])
        a.merge_state(b)
        _assert_allclose(a.compute(), first, msg=f"{name}: merge_state != one-shot")

    # 5) reset restores defaults
    metric.reset()
    metric.update(*data[0])
    fresh0 = ctor()
    fresh0.update(*data[0])
    _assert_allclose(metric.compute(), fresh0.compute(), msg=f"{name}: reset did not restore defaults")

    # 6) state_dict round-trip (persistence on, like reference persistent states)
    m_src = ctor()
    for batch in data:
        m_src.update(*batch)
    m_src.persistent(True)
    sd = m_src.state_dict()
    m_dst = ctor()
    m_dst.load_state_dict(sd)
    _assert_allclose(m_dst.compute(), first, msg=f"{name}: state_dict round-trip broke state")

    # 7) forward contract (reference metric.py:287): returns THIS batch's value
    # while accumulating globally — batch value == fresh-metric(single batch),
    # and the accumulation afterwards equals plain sequential updates
    if name not in _SKIP_FORWARD:
        m_fwd = ctor()
        batch_val = m_fwd(*data[0])
        fresh1 = ctor()
        fresh1.update(*data[0])
        _assert_allclose(batch_val, fresh1.compute(), msg=f"{name}: forward batch value != single-batch compute")
        for batch in data[1:]:
            m_fwd(*batch)
        _assert_allclose(m_fwd.compute(), first, msg=f"{name}: forward accumulation != update accumulation")
