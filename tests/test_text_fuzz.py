"""Text-domain fuzz vs the reference library on random unicode/CJK corpora
(VERDICT r3 #8 — text was the one domain with no fuzz battery).

The generator mixes ASCII words, CJK runs, accented latin, digits and
punctuation with variable sentence/corpus sizes and multi-reference targets, so
tokenizer edge behavior (13a punctuation splits, `intl` unicode categories,
`zh` han-character isolation, char mode) is exercised on content the fixed
mini-corpus in test_text.py never reaches.
"""

from __future__ import annotations

import numpy as np
import pytest

import torchmetrics_tpu.functional.text as F
from tests.helpers import _assert_allclose
from tests.oracle import reference_torchmetrics

_ASCII = ["cat", "on", "the", "mat", "hello", "world", "quick", "brown", "fox", "jumps"]
_CJK = "猫在垫子上你好世界快狐狸跳懒狗日本語のテスト한국어시험"
_ACCENT = ["wörld", "naïve", "café", "señor", "Zürich", "résumé"]
_PUNCT = [",", ".", "!", "?", ";", ":", "—", "(", ")", '"', "'s", "-", "..."]
_DIGIT = ["123", "3.14", "2-3", "1,000", "42"]


def _oracle():
    tm_ref = reference_torchmetrics()
    if tm_ref is None:
        pytest.skip("oracle unavailable")
    return tm_ref


def _rand_sentence(rng: np.random.Generator, min_tokens: int = 1) -> str:
    n = int(rng.integers(min_tokens, 14))
    parts = []
    for _ in range(n):
        kind = rng.random()
        if kind < 0.45:
            parts.append(str(rng.choice(_ASCII)))
        elif kind < 0.6:
            k = int(rng.integers(1, 5))
            start = int(rng.integers(0, len(_CJK) - k))
            parts.append(_CJK[start : start + k])
        elif kind < 0.72:
            parts.append(str(rng.choice(_ACCENT)))
        elif kind < 0.85:
            parts.append(str(rng.choice(_DIGIT)))
        else:
            parts.append(str(rng.choice(_ASCII)) + str(rng.choice(_PUNCT)))
    return " ".join(parts)


def _rand_corpus(rng: np.random.Generator, n: int, n_refs_max: int = 3):
    preds = [_rand_sentence(rng) for _ in range(n)]
    target = [[_rand_sentence(rng) for _ in range(int(rng.integers(1, n_refs_max + 1)))] for _ in range(n)]
    return preds, target


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("tokenize", ["none", "13a", "char", "intl", "zh"])
def test_sacre_bleu_fuzz(seed, tokenize):
    tm_ref = _oracle()
    rng = np.random.default_rng(100 + seed)
    preds, target = _rand_corpus(rng, 8)
    for lowercase in (False, True):
        ours = F.sacre_bleu_score(preds, target, tokenize=tokenize, lowercase=lowercase)
        ref = tm_ref.functional.text.sacre_bleu_score(preds, target, tokenize=tokenize, lowercase=lowercase)
        _assert_allclose(ours, ref.numpy(), atol=1e-5, msg=f"tokenize={tokenize} lowercase={lowercase}")


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("n_gram,smooth", [(2, False), (4, False), (4, True)])
def test_bleu_fuzz(seed, n_gram, smooth):
    tm_ref = _oracle()
    rng = np.random.default_rng(200 + seed)
    preds, target = _rand_corpus(rng, 10)
    ours = F.bleu_score(preds, target, n_gram=n_gram, smooth=smooth)
    ref = tm_ref.functional.text.bleu_score(preds, target, n_gram=n_gram, smooth=smooth)
    _assert_allclose(ours, ref.numpy(), atol=1e-5)


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("n_char_order,n_word_order,whitespace", [(6, 2, False), (6, 0, False), (4, 2, True)])
def test_chrf_fuzz(seed, n_char_order, n_word_order, whitespace):
    tm_ref = _oracle()
    rng = np.random.default_rng(300 + seed)
    preds, target = _rand_corpus(rng, 8)
    kwargs = dict(n_char_order=n_char_order, n_word_order=n_word_order, whitespace=whitespace)
    ours = F.chrf_score(preds, target, **kwargs)
    ref = tm_ref.functional.text.chrf_score(preds, target, **kwargs)
    _assert_allclose(ours, ref.numpy(), atol=1e-5)


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("accumulate", ["avg", "best"])
@pytest.mark.parametrize("use_stemmer", [False, True])
def test_rouge_fuzz(seed, accumulate, use_stemmer):
    tm_ref = _oracle()
    pytest.importorskip("nltk") if use_stemmer else None
    rng = np.random.default_rng(400 + seed)
    preds, target = _rand_corpus(rng, 6)
    keys = ("rouge1", "rouge2", "rougeL")
    try:
        ref = tm_ref.functional.text.rouge_score(
            preds, target, accumulate=accumulate, use_stemmer=use_stemmer, rouge_keys=keys
        )
    except (ModuleNotFoundError, ValueError) as err:
        pytest.skip(f"reference rouge unavailable: {err}")
    ours = F.rouge_score(preds, target, accumulate=accumulate, use_stemmer=use_stemmer, rouge_keys=keys)
    for k in ours:
        _assert_allclose(ours[k], ref[k].numpy(), atol=1e-5, msg=k)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_asr_fuzz(seed):
    """wer/cer/mer/wil/wip on random unicode corpora."""
    tm_ref = _oracle()
    rng = np.random.default_rng(500 + seed)
    preds = [_rand_sentence(rng) for _ in range(10)]
    target = [_rand_sentence(rng) for _ in range(10)]
    for name in ("word_error_rate", "char_error_rate", "match_error_rate", "word_information_lost",
                 "word_information_preserved"):
        ours = getattr(F, name)(preds, target)
        ref = getattr(tm_ref.functional.text, name)(preds, target)
        _assert_allclose(ours, ref.numpy(), atol=1e-6, msg=name)


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("normalize,no_punctuation,asian_support", [
    (False, False, False), (True, True, False), (False, False, True), (True, False, True),
])
def test_ter_fuzz(seed, normalize, no_punctuation, asian_support):
    tm_ref = _oracle()
    rng = np.random.default_rng(600 + seed)
    preds, target = _rand_corpus(rng, 6, n_refs_max=2)
    kwargs = dict(normalize=normalize, no_punctuation=no_punctuation, asian_support=asian_support)
    ours = F.translation_edit_rate(preds, target, **kwargs)
    ref = tm_ref.functional.text.translation_edit_rate(preds, target, **kwargs)
    _assert_allclose(ours, ref.numpy(), atol=1e-5)


@pytest.mark.parametrize("seed", [0, 1])
def test_edit_distance_fuzz(seed):
    tm_ref = _oracle()
    rng = np.random.default_rng(700 + seed)
    preds = [_rand_sentence(rng) for _ in range(8)]
    target = [_rand_sentence(rng) for _ in range(8)]
    for reduction in ("mean", "sum", "none"):
        ours = F.edit_distance(preds, target, reduction=reduction)
        ref = tm_ref.functional.text.edit_distance(preds, target, reduction=reduction)
        _assert_allclose(ours, ref.numpy(), atol=1e-6, msg=f"reduction={reduction}")
