"""VMAF elementary features + NuSVR fusion tests.

``vmaf_torch`` (the reference's only backend) and the trained ``vmaf_v0.6.1``
SVM model are unavailable offline, so the features are validated by their
defining properties (identity, monotone degradation, hand-computable motion)
and the fusion engine against hand-computed RBF kernels on a synthetic
libvmaf-format model file.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import torchmetrics_tpu as tm
from torchmetrics_tpu.functional.video.vmaf import (
    VmafModel,
    adm_features,
    calculate_luma,
    motion_features,
    vif_features,
    vmaf_features,
    video_multi_method_assessment_fusion,
)


def _videos(seed=0, b=1, f=4, h=36, w=44):
    rng = np.random.default_rng(seed)
    base = rng.random((b, 3, f, h, w)).astype(np.float32)
    return base


def _smooth_video(b=1, f=4, h=48, w=48):
    """Low-frequency content so VIF/ADM statistics are well-conditioned."""
    y, x = np.mgrid[:h, :w] / h
    frames = np.stack([np.sin(4 * np.pi * (x + 0.08 * i)) * np.cos(3 * np.pi * y) for i in range(f)])
    vid = np.repeat(frames[None, None], 3, axis=1).astype(np.float32) * 0.4 + 0.5
    return np.broadcast_to(vid, (b, 3, f, h, w)).copy()


class TestElementaryFeatures:
    def test_identity_is_perfect(self):
        vid = _smooth_video()
        luma = calculate_luma(vid)
        vifs = vif_features(luma, luma)
        for k, v in vifs.items():
            np.testing.assert_allclose(np.asarray(v), 1.0, atol=1e-4, err_msg=k)
        adms = adm_features(luma, luma)
        for k, v in adms.items():
            np.testing.assert_allclose(np.asarray(v), 1.0, atol=1e-3, err_msg=k)

    def test_static_video_zero_motion(self):
        vid = np.broadcast_to(_smooth_video(f=1)[:, :, :1], (1, 3, 5, 48, 48)).copy()
        motion, motion2 = motion_features(calculate_luma(vid))
        np.testing.assert_allclose(np.asarray(motion), 0.0, atol=1e-4)
        np.testing.assert_allclose(np.asarray(motion2), 0.0, atol=1e-4)

    def test_motion_matches_hand_calc(self):
        """Two constant frames differing by a constant offset: blur preserves the
        offset, so motion = |offset| * 255."""
        vid = np.zeros((1, 3, 2, 32, 32), np.float32)
        vid[:, :, 1] = 0.1
        motion, motion2 = motion_features(calculate_luma(vid))
        np.testing.assert_allclose(np.asarray(motion)[0], [0.0, 25.5], atol=1e-3)
        np.testing.assert_allclose(np.asarray(motion2)[0], [0.0, 25.5], atol=1e-3)

    def test_degradation_monotone(self):
        vid = _smooth_video()
        luma = calculate_luma(vid)
        rng = np.random.default_rng(1)
        noise = rng.normal(size=luma.shape).astype(np.float32)
        vif_mid = np.asarray(vif_features(luma, luma + 8 * noise)["vif_scale0"]).mean()
        vif_bad = np.asarray(vif_features(luma, luma + 30 * noise)["vif_scale0"]).mean()
        assert 1.0 > vif_mid > vif_bad
        adm_mid = np.asarray(adm_features(luma, luma + 8 * noise)["adm2"]).mean()
        assert adm_mid < 1.0 + 1e-3

    def test_feature_dict_keys_and_shapes(self):
        vid = _videos(b=2, f=3)
        out = vmaf_features(vid, vid)
        expected = {
            "integer_motion", "integer_motion2", "integer_adm2",
            *(f"integer_adm_scale{i}" for i in range(4)),
            *(f"integer_vif_scale{i}" for i in range(4)),
        }
        assert set(out) == expected
        for v in out.values():
            assert np.asarray(v).shape == (2, 3)

    def test_input_validation(self):
        with pytest.raises(ValueError, match="batch, 3, frames"):
            vmaf_features(np.zeros((2, 10, 10)), np.zeros((2, 10, 10)))


def _toy_model(tmp_path, feature_names, n_sv=3, seed=0):
    rng = np.random.default_rng(seed)
    blob = {
        "model_dict": {
            "feature_names": feature_names,
            "norm_type": "linear_rescale",
            # entry 0 is the score normalization, rest per-feature
            "slopes": [0.012, *np.round(rng.uniform(0.5, 2, len(feature_names)), 3).tolist()],
            "intercepts": [-0.3, *np.round(rng.uniform(-1, 1, len(feature_names)), 3).tolist()],
            "model": {
                "gamma": 0.04,
                "rho": -1.2,
                "sv_coef": np.round(rng.uniform(-2, 2, n_sv), 3).tolist(),
                "support_vectors": np.round(rng.uniform(0, 1, (n_sv, len(feature_names))), 3).tolist(),
            },
            "score_clip": [0.0, 100.0],
        }
    }
    path = tmp_path / "toy_vmaf_model.json"
    path.write_text(json.dumps(blob))
    return str(path), blob["model_dict"]


class TestFusion:
    FEATURES = [
        "integer_motion2", "integer_adm2",
        "integer_vif_scale0", "integer_vif_scale1", "integer_vif_scale2", "integer_vif_scale3",
    ]

    def test_nusvr_matches_hand_calc(self, tmp_path):
        path, d = _toy_model(tmp_path, self.FEATURES)
        model = VmafModel.from_file(path)
        rng = np.random.default_rng(2)
        feats = {name: rng.uniform(0, 1, (2, 3)) for name in self.FEATURES}
        got = model.predict(feats)
        # hand computation
        x = np.stack([feats[n] for n in self.FEATURES], -1).reshape(-1, 6)
        xn = np.asarray(d["slopes"][1:]) * x + np.asarray(d["intercepts"][1:])
        sv = np.asarray(d["model"]["support_vectors"])
        k = np.exp(-d["model"]["gamma"] * ((xn[:, None] - sv[None]) ** 2).sum(-1))
        y = (np.asarray(d["model"]["sv_coef"]) * k).sum(-1) - d["model"]["rho"]
        y = (y - d["intercepts"][0]) / d["slopes"][0]
        y = np.clip(y, 0, 100).reshape(2, 3)
        np.testing.assert_allclose(got, y, rtol=1e-12)

    def test_fused_score_end_to_end(self, tmp_path):
        path, _ = _toy_model(tmp_path, self.FEATURES)
        vid = _videos(b=2, f=3)
        score = np.asarray(video_multi_method_assessment_fusion(vid, vid, model_path=path))
        assert score.shape == (2, 3)
        assert (score >= 0).all() and (score <= 100).all()
        out = video_multi_method_assessment_fusion(vid, vid, features=True, model_path=path)
        assert "vmaf" in out and "integer_adm2" in out

    def test_class_accumulates(self, tmp_path):
        path, _ = _toy_model(tmp_path, self.FEATURES)
        m = tm.VideoMultiMethodAssessmentFusion(model_path=path)
        m.update(_videos(seed=1, f=2), _videos(seed=2, f=2))
        m.update(_videos(seed=3, f=3), _videos(seed=4, f=3))
        out = np.asarray(m.compute())
        assert out.shape == (5,)
        mf = tm.VideoMultiMethodAssessmentFusion(features=True, model_path=path)
        mf.update(_videos(seed=1, f=2), _videos(seed=2, f=2))
        d = mf.compute()
        assert np.asarray(d["vmaf"]).shape == (2,)
        assert np.asarray(d["integer_vif_scale3"]).shape == (2,)

    def test_gate_without_any_path(self):
        with pytest.raises(ModuleNotFoundError, match="vmaf"):
            video_multi_method_assessment_fusion(_videos(), _videos())
        with pytest.raises(ModuleNotFoundError, match="vmaf"):
            tm.VideoMultiMethodAssessmentFusion()


def test_libvmaf_feature_name_mapping(tmp_path):
    """Real libvmaf model files name features VMAF_feature_<x>_score — they must
    resolve to the in-tree integer_<x> keys."""
    from torchmetrics_tpu.functional.video.vmaf import _canonical_feature_key

    assert _canonical_feature_key("VMAF_feature_adm2_score") == "integer_adm2"
    assert _canonical_feature_key("'VMAF_feature_motion2_score'") == "integer_motion2"
    assert _canonical_feature_key("VMAF_feature_vif_scale0_score") == "integer_vif_scale0"
    assert _canonical_feature_key("integer_adm2") == "integer_adm2"

    path, _ = _toy_model(
        tmp_path,
        [
            "VMAF_feature_motion2_score", "VMAF_feature_adm2_score",
            "VMAF_feature_vif_scale0_score", "VMAF_feature_vif_scale1_score",
            "VMAF_feature_vif_scale2_score", "VMAF_feature_vif_scale3_score",
        ],
    )
    vid = _videos(b=1, f=2)
    score = np.asarray(video_multi_method_assessment_fusion(vid, vid, model_path=path))
    assert score.shape == (1, 2) and np.isfinite(score).all()
