"""Stat-scores family vs sklearn (reference tests/unittests/classification/test_accuracy.py
et al: golden rule — every metric tested against an independent reference over random
inputs, functional + class + multi-device)."""

from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn import metrics as sk

import torchmetrics_tpu.functional as F
from torchmetrics_tpu.classification import (
    BinaryAccuracy,
    BinaryF1Score,
    BinaryPrecision,
    BinaryRecall,
    BinarySpecificity,
    MulticlassAccuracy,
    MulticlassF1Score,
    MulticlassFBetaScore,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelAccuracy,
    MultilabelF1Score,
    MultilabelPrecision,
    MultilabelRecall,
)
from conftest import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES, THRESHOLD, seed_all
from helpers import MetricTester

_rng = seed_all(7)

# binary case: probs in [0,1]
_bin_preds = _rng.random((NUM_BATCHES, BATCH_SIZE)).astype(np.float32)
_bin_target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE))

# multiclass case: logits (N, C)
_mc_logits = _rng.normal(size=(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32)
_mc_target = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))

# multilabel case: probs (N, C)
_ml_preds = _rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32)
_ml_target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))


def _sk_binary(fn):
    def ref(preds, target):
        return fn(target, (preds >= THRESHOLD).astype(int))

    return ref


def _sk_multiclass(fn):
    def ref(preds, target):
        return fn(target, preds.argmax(-1))

    return ref


def _sk_multilabel(fn):
    def ref(preds, target):
        return fn(target.reshape(-1, NUM_CLASSES), (preds >= THRESHOLD).astype(int).reshape(-1, NUM_CLASSES))

    return ref


_mc_labels = list(range(NUM_CLASSES))

BINARY_CASES = [
    (BinaryAccuracy, F.binary_accuracy, _sk_binary(sk.accuracy_score), {}),
    (BinaryPrecision, F.binary_precision, _sk_binary(partial(sk.precision_score, zero_division=0)), {}),
    (BinaryRecall, F.binary_recall, _sk_binary(partial(sk.recall_score, zero_division=0)), {}),
    (BinaryF1Score, F.binary_f1_score, _sk_binary(partial(sk.f1_score, zero_division=0)), {}),
    (
        BinarySpecificity,
        F.binary_specificity,
        _sk_binary(lambda t, p: sk.recall_score(1 - np.asarray(t), 1 - np.asarray(p), zero_division=0)),
        {},
    ),
]


@pytest.mark.parametrize("metric_class,functional,ref,extra", BINARY_CASES)
class TestBinaryFamily(MetricTester):
    def test_functional(self, metric_class, functional, ref, extra):
        self.run_functional_metric_test(_bin_preds, _bin_target, functional, ref, extra)

    def test_class(self, metric_class, functional, ref, extra):
        self.run_class_metric_test(_bin_preds, _bin_target, metric_class, ref, extra)

    def test_merge(self, metric_class, functional, ref, extra):
        self.run_merge_state_test(_bin_preds, _bin_target, metric_class, ref, extra)

    def test_ingraph(self, metric_class, functional, ref, extra):
        self.run_ingraph_sharded_test(_bin_preds, _bin_target, metric_class, ref, extra)


def _mc_cases():
    cases = []
    for average in ["micro", "macro", "weighted", None]:
        sk_avg = average if average else None
        cases.append((
            MulticlassAccuracy,
            partial(F.multiclass_accuracy, num_classes=NUM_CLASSES, average=average),
            _sk_multiclass(
                sk.accuracy_score
                if average == "micro"
                else partial(sk.recall_score, average=sk_avg, labels=_mc_labels, zero_division=0)
            ),
            {"num_classes": NUM_CLASSES, "average": average},
            f"acc-{average}",
        ))
        for metric_class, functional, sk_fn, nm in [
            (MulticlassPrecision, F.multiclass_precision, sk.precision_score, "prec"),
            (MulticlassRecall, F.multiclass_recall, sk.recall_score, "rec"),
            (MulticlassF1Score, F.multiclass_f1_score, sk.f1_score, "f1"),
        ]:
            cases.append((
                metric_class,
                partial(functional, num_classes=NUM_CLASSES, average=average),
                _sk_multiclass(partial(sk_fn, average=sk_avg, labels=_mc_labels, zero_division=0)),
                {"num_classes": NUM_CLASSES, "average": average},
                f"{nm}-{average}",
            ))
    cases.append((
        MulticlassFBetaScore,
        partial(F.multiclass_fbeta_score, beta=2.0, num_classes=NUM_CLASSES, average="macro"),
        _sk_multiclass(partial(sk.fbeta_score, beta=2.0, average="macro", labels=_mc_labels, zero_division=0)),
        {"beta": 2.0, "num_classes": NUM_CLASSES, "average": "macro"},
        "fbeta2-macro",
    ))
    return cases


_MC_CASES = _mc_cases()


@pytest.mark.parametrize(
    "metric_class,functional,ref,extra", [c[:4] for c in _MC_CASES], ids=[c[4] for c in _MC_CASES]
)
class TestMulticlassFamily(MetricTester):
    def test_functional(self, metric_class, functional, ref, extra):
        self.run_functional_metric_test(_mc_logits, _mc_target, functional, ref, {})

    def test_class(self, metric_class, functional, ref, extra):
        self.run_class_metric_test(_mc_logits, _mc_target, metric_class, ref, extra)

    def test_merge(self, metric_class, functional, ref, extra):
        self.run_merge_state_test(_mc_logits, _mc_target, metric_class, ref, extra)

    def test_ingraph(self, metric_class, functional, ref, extra):
        self.run_ingraph_sharded_test(_mc_logits, _mc_target, metric_class, ref, extra)


ML_CASES = [
    (
        MultilabelAccuracy,
        partial(F.multilabel_accuracy, num_labels=NUM_CLASSES, average="macro"),
        # sklearn has no per-label accuracy avg; macro accuracy over labels == mean over
        # label columns of accuracy
        _sk_multilabel(
            lambda t, p: np.mean([sk.accuracy_score(t[:, i], p[:, i]) for i in range(NUM_CLASSES)])
        ),
        {"num_labels": NUM_CLASSES, "average": "macro"},
        "mlacc-macro",
    ),
    (
        MultilabelPrecision,
        partial(F.multilabel_precision, num_labels=NUM_CLASSES, average="macro"),
        _sk_multilabel(partial(sk.precision_score, average="macro", zero_division=0)),
        {"num_labels": NUM_CLASSES, "average": "macro"},
        "mlprec-macro",
    ),
    (
        MultilabelRecall,
        partial(F.multilabel_recall, num_labels=NUM_CLASSES, average="micro"),
        _sk_multilabel(partial(sk.recall_score, average="micro", zero_division=0)),
        {"num_labels": NUM_CLASSES, "average": "micro"},
        "mlrec-micro",
    ),
    (
        MultilabelF1Score,
        partial(F.multilabel_f1_score, num_labels=NUM_CLASSES, average="weighted"),
        _sk_multilabel(partial(sk.f1_score, average="weighted", zero_division=0)),
        {"num_labels": NUM_CLASSES, "average": "weighted"},
        "mlf1-weighted",
    ),
]


@pytest.mark.parametrize(
    "metric_class,functional,ref,extra", [c[:4] for c in ML_CASES], ids=[c[4] for c in ML_CASES]
)
class TestMultilabelFamily(MetricTester):
    def test_functional(self, metric_class, functional, ref, extra):
        self.run_functional_metric_test(_ml_preds, _ml_target, functional, ref, {})

    def test_class(self, metric_class, functional, ref, extra):
        self.run_class_metric_test(_ml_preds, _ml_target, metric_class, ref, extra)

    def test_merge(self, metric_class, functional, ref, extra):
        self.run_merge_state_test(_ml_preds, _ml_target, metric_class, ref, extra)

    def test_ingraph(self, metric_class, functional, ref, extra):
        self.run_ingraph_sharded_test(_ml_preds, _ml_target, metric_class, ref, extra)


def test_ignore_index_binary():
    target = np.array([0, 1, -1, 1, 0, -1])
    preds = np.array([0.9, 0.8, 0.7, 0.3, 0.1, 0.9])
    acc = float(F.binary_accuracy(jnp.asarray(preds), jnp.asarray(target), ignore_index=-1))
    # valid: (0,0.9)->wrong, (1,0.8)->right, (1,0.3)->wrong, (0,0.1)->right
    assert acc == pytest.approx(0.5)


def test_ignore_index_multiclass():
    target = np.array([0, 1, 2, -1, 1])
    preds = np.array([0, 1, 1, 2, 1])
    acc = float(F.multiclass_accuracy(jnp.asarray(preds), jnp.asarray(target), num_classes=3, average="micro", ignore_index=-1))
    assert acc == pytest.approx(3 / 4)


def test_top_k_accuracy():
    preds = np.asarray([
        [0.5, 0.3, 0.2],
        [0.1, 0.6, 0.3],
        [0.2, 0.3, 0.5],
    ], dtype=np.float32)
    target = np.asarray([1, 1, 0])
    top1 = float(F.multiclass_accuracy(jnp.asarray(preds), jnp.asarray(target), num_classes=3, average="micro", top_k=1))
    top2 = float(F.multiclass_accuracy(jnp.asarray(preds), jnp.asarray(target), num_classes=3, average="micro", top_k=2))
    assert top1 == pytest.approx(1 / 3)
    assert top2 == pytest.approx(2 / 3)


def test_samplewise_multidim():
    rng = seed_all(3)
    preds = rng.integers(0, 2, (4, 10))
    target = rng.integers(0, 2, (4, 10))
    out = F.binary_accuracy(jnp.asarray(preds), jnp.asarray(target), multidim_average="samplewise")
    assert out.shape == (4,)
    expected = (preds == target).mean(-1)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-6)


def test_stat_scores_output_shape():
    out = F.multiclass_stat_scores(
        jnp.asarray(_mc_logits[0]), jnp.asarray(_mc_target[0]), num_classes=NUM_CLASSES, average=None
    )
    assert out.shape == (NUM_CLASSES, 5)
    out_micro = F.multiclass_stat_scores(
        jnp.asarray(_mc_logits[0]), jnp.asarray(_mc_target[0]), num_classes=NUM_CLASSES, average="micro"
    )
    assert out_micro.shape == (5,)
    # support equals class occurrence counts
    np.testing.assert_array_equal(
        np.asarray(out[:, 4]), np.bincount(_mc_target[0], minlength=NUM_CLASSES)
    )


def test_task_facades_route():
    from torchmetrics_tpu import Accuracy
    from torchmetrics_tpu.classification import MulticlassAccuracy as MCA

    m = Accuracy(task="multiclass", num_classes=NUM_CLASSES)
    assert isinstance(m, MCA)
    f = F.accuracy(
        jnp.asarray(_mc_logits[0]), jnp.asarray(_mc_target[0]), task="multiclass", num_classes=NUM_CLASSES,
        average="micro",
    )
    ref = sk.accuracy_score(_mc_target[0], _mc_logits[0].argmax(-1))
    assert float(f) == pytest.approx(ref, abs=1e-6)
