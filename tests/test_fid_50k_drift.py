"""f32 accumulation drift of the FID running states at BASELINE scale (VERDICT r3 #3).

The reference keeps f64 states (``/root/reference/src/torchmetrics/image/fid.py:376-381``);
we accumulate on-device in f32 (TPU f64 is emulated) and run the final Gaussian
algebra in f64 on host. This test streams BASELINE's 50k images per side through
the REAL metric update path and pins the measured drift against a full-f64 oracle.

Measured (50k x 2048, inception-like positive features, batch 500):
- running ``features_sum``  max rel err ~4.3e-7
- running ``cov_sum``       max rel err ~3.9e-7
- final FID                 rel err ~2.2e-7  (abs ~2e-6 on FID ~9.3)

The states stay at f32-rounding level (no O(n) error growth) because inception
features are post-ReLU nonnegative: every summand has the same sign, so the
running sums grow monotonically and sequential f32 addition random-walks at
~sqrt(n)*eps relative. Compensated (Kahan) summation is therefore NOT needed —
this test fails if a regression ever pushes drift past 50x the measured bound.

KID/IS/MiFID keep raw feature rows (no running reduction), so their only f32
effect is per-feature storage rounding; the MMD algebra is f64 on host.
"""

from __future__ import annotations

import numpy as np
import pytest

import torchmetrics_tpu as tm

jnp = pytest.importorskip("jax.numpy")

F, N, B = 2048, 50_000, 500


class _Identity:
    num_features = F

    def __call__(self, x):
        return x


@pytest.mark.slow
def test_fid_f32_state_drift_at_50k():
    rng = np.random.default_rng(0)
    scales = rng.uniform(0.05, 1.5, F)

    fid = tm.FrechetInceptionDistance(feature=_Identity(), normalize=True)
    sum_r64 = np.zeros(F)
    cov_r64 = np.zeros((F, F))
    sum_f64 = np.zeros(F)
    cov_f64 = np.zeros((F, F))
    for _ in range(N // B):
        real = (np.abs(rng.standard_normal((B, F))) * scales).astype(np.float32)
        fake = (np.abs(rng.standard_normal((B, F))) * scales * 1.02 + 0.01).astype(np.float32)
        fid.update(jnp.asarray(real), real=True)
        fid.update(jnp.asarray(fake), real=False)
        r64 = real.astype(np.float64)
        f64v = fake.astype(np.float64)
        sum_r64 += r64.sum(0)
        cov_r64 += r64.T @ r64
        sum_f64 += f64v.sum(0)
        cov_f64 += f64v.T @ f64v

    # state-level drift of the f32 running sums
    got_sum = np.asarray(fid.real_features_sum, np.float64)
    got_cov = np.asarray(fid.real_features_cov_sum, np.float64)
    assert np.abs(got_sum - sum_r64).max() / np.abs(sum_r64).max() < 2e-5
    assert np.abs(got_cov - cov_r64).max() / np.abs(cov_r64).max() < 2e-5

    # end-to-end FID drift vs the all-f64 oracle (same final algebra)
    from torchmetrics_tpu.image.generative import _compute_fid

    mu_r, mu_f = sum_r64 / N, sum_f64 / N
    cov_r = (cov_r64 - N * np.outer(mu_r, mu_r)) / (N - 1)
    cov_f = (cov_f64 - N * np.outer(mu_f, mu_f)) / (N - 1)
    fid_f64 = _compute_fid(mu_r, cov_r, mu_f, cov_f)
    fid_f32 = float(fid.compute())
    assert fid_f32 == pytest.approx(fid_f64, rel=1e-5, abs=1e-4)
