"""Detection tower parity tests.

Oracles: the reference's torchvision-backed IoU family and its pure-torch mAP template
(``/root/reference/src/torchmetrics/detection/_mean_ap.py``), both runnable through the
test-only torchvision/pycocotools stubs in ``tests/_oracle_stubs``.

The legacy oracle excludes area-ignored gts from matching wholesale, while this repo
follows pycocotools (ignored gts matchable, det then ignored) — so parity fixtures keep
every box inside one COCO area bucket, where the two protocols coincide.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from tests.helpers import _assert_allclose
from tests.oracle import reference_torchmetrics

from torchmetrics_tpu.detection import (
    CompleteIntersectionOverUnion,
    DistanceIntersectionOverUnion,
    GeneralizedIntersectionOverUnion,
    IntersectionOverUnion,
    MeanAveragePrecision,
)
from torchmetrics_tpu.functional.detection import (
    complete_intersection_over_union,
    distance_intersection_over_union,
    generalized_intersection_over_union,
    intersection_over_union,
)

_SEED = 7


def _rand_boxes(rng, n, lo=0.0, hi=400.0, min_wh=100.0, max_wh=200.0):
    """xyxy boxes whose areas all land in the COCO 'large' bucket (>96^2)."""
    xy = rng.uniform(lo, hi, size=(n, 2))
    wh = rng.uniform(min_wh, max_wh, size=(n, 2))
    return np.concatenate([xy, xy + wh], axis=-1).astype(np.float32)


def _det_batches(num_updates=3, imgs_per_update=2, num_classes=3, seed=_SEED, min_boxes=0):
    """min_boxes=1 sidesteps a reference crash: its per-class IoU compute boolean-indexes
    a (N,N) zero matrix with a length-0 label mask when an image has dets but no gts."""
    rng = np.random.default_rng(seed)
    batches = []
    for _ in range(num_updates):
        preds, target = [], []
        for _ in range(imgs_per_update):
            nd = int(rng.integers(min_boxes, 8))
            ng = int(rng.integers(min_boxes, 6))
            preds.append({
                "boxes": _rand_boxes(rng, nd),
                "scores": rng.uniform(0.1, 1.0, nd).astype(np.float32),
                "labels": rng.integers(0, num_classes, nd).astype(np.int32),
            })
            target.append({
                "boxes": _rand_boxes(rng, ng),
                "labels": rng.integers(0, num_classes, ng).astype(np.int32),
            })
        batches.append((preds, target))
    return batches


def _to_torch(items, keys):
    import torch

    return [{k: torch.as_tensor(np.asarray(d[k])) for k in keys if k in d} for d in items]


FUNCTIONAL_PAIRS = [
    (intersection_over_union, "intersection_over_union"),
    (generalized_intersection_over_union, "generalized_intersection_over_union"),
    (distance_intersection_over_union, "distance_intersection_over_union"),
    (complete_intersection_over_union, "complete_intersection_over_union"),
]


@pytest.mark.parametrize("fn,ref_name", FUNCTIONAL_PAIRS, ids=[p[1] for p in FUNCTIONAL_PAIRS])
@pytest.mark.parametrize("aggregate", [True, False])
def test_iou_functional_parity(fn, ref_name, aggregate):
    tm = reference_torchmetrics()
    if tm is None:
        pytest.skip("oracle unavailable")
    import torch

    ref_fn = getattr(tm.functional.detection, ref_name)
    rng = np.random.default_rng(_SEED)
    preds = _rand_boxes(rng, 5)
    target = _rand_boxes(rng, 5)
    ours = fn(jnp.asarray(preds), jnp.asarray(target), aggregate=aggregate)
    ref = ref_fn(torch.as_tensor(preds), torch.as_tensor(target), aggregate=aggregate)
    _assert_allclose(ours, ref.numpy(), atol=1e-5)
    # thresholded variant
    ours_t = fn(jnp.asarray(preds), jnp.asarray(target), iou_threshold=0.3, replacement_val=-1, aggregate=aggregate)
    ref_t = ref_fn(torch.as_tensor(preds), torch.as_tensor(target), iou_threshold=0.3, replacement_val=-1, aggregate=aggregate)
    _assert_allclose(ours_t, ref_t.numpy(), atol=1e-5)


CLASS_PAIRS = [
    (IntersectionOverUnion, "IntersectionOverUnion"),
    (GeneralizedIntersectionOverUnion, "GeneralizedIntersectionOverUnion"),
    (DistanceIntersectionOverUnion, "DistanceIntersectionOverUnion"),
    (CompleteIntersectionOverUnion, "CompleteIntersectionOverUnion"),
]


@pytest.mark.parametrize("cls,ref_name", CLASS_PAIRS, ids=[p[1] for p in CLASS_PAIRS])
@pytest.mark.parametrize("respect_labels", [True, False])
@pytest.mark.parametrize("class_metrics", [True, False])
def test_iou_class_parity(cls, ref_name, respect_labels, class_metrics):
    tm = reference_torchmetrics()
    if tm is None:
        pytest.skip("oracle unavailable")
    ref_cls = getattr(tm.detection, ref_name)
    ours = cls(respect_labels=respect_labels, class_metrics=class_metrics)
    ref = ref_cls(respect_labels=respect_labels, class_metrics=class_metrics)
    for preds, target in _det_batches(min_boxes=1 if class_metrics else 0):
        ours.update(preds, target)
        ref.update(_to_torch(preds, ("boxes", "scores", "labels")), _to_torch(target, ("boxes", "labels")))
    r_ours = ours.compute()
    r_ref = {k: v.numpy() for k, v in ref.compute().items()}
    assert set(r_ours) == set(r_ref)
    _assert_allclose(r_ours, r_ref, atol=1e-5)


def test_iou_class_merge_matches_single():
    batches = _det_batches(num_updates=3)
    single = IntersectionOverUnion(class_metrics=True)
    shards = [IntersectionOverUnion(class_metrics=True) for _ in range(3)]
    for (preds, target), shard in zip(batches, shards):
        single.update(preds, target)
        shard.update(preds, target)
    merged = shards[0]
    merged.merge_state(shards[1])
    merged.merge_state(shards[2])
    _assert_allclose(merged.compute(), single.compute(), atol=1e-6)


@pytest.mark.parametrize("class_metrics", [False, True])
def test_map_parity_with_reference_template(class_metrics):
    tm = reference_torchmetrics()
    if tm is None:
        pytest.skip("oracle unavailable")
    from torchmetrics.detection._mean_ap import MeanAveragePrecision as RefMAP  # type: ignore

    ours = MeanAveragePrecision(class_metrics=class_metrics)
    ref = RefMAP(class_metrics=class_metrics)
    for preds, target in _det_batches(num_updates=4, imgs_per_update=3, num_classes=3, seed=11):
        ours.update(preds, target)
        ref.update(_to_torch(preds, ("boxes", "scores", "labels")), _to_torch(target, ("boxes", "labels")))
    r_ours = ours.compute()
    r_ref = {k: np.asarray(v) for k, v in ref.compute().items()}
    for key in ("map", "map_50", "map_75", "map_large", "map_small", "map_medium",
                "mar_1", "mar_10", "mar_100", "mar_large", "classes",
                "map_per_class", "mar_100_per_class"):
        _assert_allclose(r_ours[key], np.squeeze(r_ref[key]), atol=1e-6, msg=f"key={key}")


def test_map_merge_matches_single():
    batches = _det_batches(num_updates=3, imgs_per_update=2, seed=23)
    single = MeanAveragePrecision()
    shards = [MeanAveragePrecision() for _ in range(3)]
    for (preds, target), shard in zip(batches, shards):
        single.update(preds, target)
        shard.update(preds, target)
    merged = shards[0]
    merged.merge_state(shards[1])
    merged.merge_state(shards[2])
    _assert_allclose(merged.compute(), single.compute(), atol=1e-6)


def test_map_forward_equals_fresh_compute():
    preds, target = _det_batches(num_updates=1, seed=3)[0]
    m = MeanAveragePrecision()
    val = m(preds, target)
    fresh = MeanAveragePrecision()
    fresh.update(preds, target)
    _assert_allclose(val, fresh.compute(), atol=1e-6)


def test_map_docstring_example():
    preds = [dict(boxes=jnp.array([[258.0, 41.0, 606.0, 285.0]]), scores=jnp.array([0.536]), labels=jnp.array([0]))]
    target = [dict(boxes=jnp.array([[214.0, 41.0, 562.0, 285.0]]), labels=jnp.array([0]))]
    m = MeanAveragePrecision()
    m.update(preds, target)
    out = m.compute()
    assert np.isclose(float(out["map"]), 0.6, atol=1e-6)
    assert float(out["map_50"]) == 1.0
    assert float(out["map_75"]) == 1.0
    assert float(out["map_medium"]) == -1.0
    assert np.isclose(float(out["mar_1"]), 0.6, atol=1e-6)


def test_map_empty_and_missing_sides():
    m = MeanAveragePrecision()
    # image with dets but no gts + image with gts but no dets
    preds = [
        dict(boxes=_rand_boxes(np.random.default_rng(0), 2), scores=np.array([0.5, 0.4], np.float32),
             labels=np.array([0, 0], np.int32)),
        dict(boxes=np.zeros((0, 4), np.float32), scores=np.zeros(0, np.float32), labels=np.zeros(0, np.int32)),
    ]
    target = [
        dict(boxes=np.zeros((0, 4), np.float32), labels=np.zeros(0, np.int32)),
        dict(boxes=_rand_boxes(np.random.default_rng(1), 2), labels=np.array([0, 0], np.int32)),
    ]
    m.update(preds, target)
    out = m.compute()
    assert float(out["map"]) == 0.0  # all dets are FPs, all gts unmatched
    assert float(out["mar_100"]) == 0.0


def test_map_iscrowd_ignored():
    # one normal gt matched + one crowd gt: crowd det is ignored (neither tp nor fp)
    box_a = np.array([[0.0, 0.0, 100.0, 100.0]], np.float32)
    box_b = np.array([[200.0, 200.0, 320.0, 320.0]], np.float32)
    preds = [dict(boxes=np.concatenate([box_a, box_b]), scores=np.array([0.9, 0.8], np.float32),
                  labels=np.array([0, 0], np.int32))]
    target = [dict(boxes=np.concatenate([box_a, box_b]), labels=np.array([0, 0], np.int32),
                   iscrowd=np.array([0, 1], np.int32))]
    m = MeanAveragePrecision()
    m.update(preds, target)
    out = m.compute()
    assert float(out["map"]) == 1.0
    assert float(out["mar_100"]) == 1.0


def test_map_micro_pools_classes():
    # det labeled 1, gt labeled 0: macro finds nothing, micro matches them
    box = np.array([[0.0, 0.0, 100.0, 100.0]], np.float32)
    preds = [dict(boxes=box, scores=np.array([0.9], np.float32), labels=np.array([1], np.int32))]
    target = [dict(boxes=box, labels=np.array([0], np.int32))]
    macro = MeanAveragePrecision(average="macro")
    micro = MeanAveragePrecision(average="micro")
    macro.update(preds, target)
    micro.update(preds, target)
    assert float(macro.compute()["map"]) == 0.0
    assert float(micro.compute()["map"]) == 1.0


def test_map_extended_summary_shapes():
    preds, target = _det_batches(num_updates=1, seed=5)[0]
    m = MeanAveragePrecision(extended_summary=True)
    m.update(preds, target)
    out = m.compute()
    num_k = len(out["classes"])
    assert out["precision"].shape == (10, 101, num_k, 4, 3)
    assert out["recall"].shape == (10, num_k, 4, 3)
    assert out["scores"].shape == (10, 101, num_k, 4, 3)
    assert isinstance(out["ious"], dict)


def test_map_segm_exact_and_miss():
    h = w = 32
    mask_a = np.zeros((h, w), bool)
    mask_a[4:20, 4:20] = True
    mask_b = np.zeros((h, w), bool)
    mask_b[22:30, 22:30] = True
    preds = [dict(masks=np.stack([mask_a]), scores=np.array([0.8], np.float32), labels=np.array([0], np.int32))]
    target = [dict(masks=np.stack([mask_a]), labels=np.array([0], np.int32))]
    m = MeanAveragePrecision(iou_type="segm")
    m.update(preds, target)
    assert float(m.compute()["map"]) == 1.0

    m2 = MeanAveragePrecision(iou_type="segm")
    preds2 = [dict(masks=np.stack([mask_b]), scores=np.array([0.8], np.float32), labels=np.array([0], np.int32))]
    m2.update(preds2, target)
    assert float(m2.compute()["map"]) == 0.0


def test_map_coco_roundtrip(tmp_path):
    preds, target = _det_batches(num_updates=1, seed=9)[0]
    m = MeanAveragePrecision()
    m.update(preds, target)
    base = str(tmp_path / "roundtrip")
    m.tm_to_coco(base)
    preds2, target2 = MeanAveragePrecision.coco_to_tm(f"{base}_preds.json", f"{base}_target.json")
    m2 = MeanAveragePrecision()
    m2.update(preds2, target2)
    _assert_allclose(m2.compute(), m.compute(), atol=1e-5)


def test_map_input_validation_errors():
    m = MeanAveragePrecision()
    with pytest.raises(ValueError, match="Expected argument `preds` and `target` to have the same length"):
        m.update([], [dict(boxes=np.zeros((0, 4)), labels=np.zeros(0))])
    with pytest.raises(ValueError, match="Expected all dicts in `preds`"):
        m.update([dict(boxes=np.zeros((0, 4)))], [dict(boxes=np.zeros((0, 4)), labels=np.zeros(0))])
    with pytest.raises(ValueError, match="Expected argument `average`"):
        MeanAveragePrecision(average="weird")
    with pytest.raises(ValueError, match="length 3"):
        MeanAveragePrecision(max_detection_thresholds=[10])


# ---------------------------------------------------------------- panoptic quality

def _panoptic_batches(num_updates=3, b=2, h=8, w=8, seed=31):
    rng = np.random.default_rng(seed)
    things, stuffs = {0, 1}, {6, 7}
    cats = np.array(sorted(things | stuffs))
    out = []
    for _ in range(num_updates):
        def gen():
            cat = cats[rng.integers(0, len(cats), (b, h, w))]
            inst = rng.integers(0, 3, (b, h, w))
            return np.stack([cat, inst], axis=-1).astype(np.int32)
        out.append((gen(), gen()))
    return things, stuffs, out


@pytest.mark.parametrize("variant", ["pq", "mpq"])
@pytest.mark.parametrize("flags", [dict(), dict(return_per_class=True), dict(return_sq_and_rq=True)])
def test_panoptic_quality_oracle_parity(variant, flags):
    tm = reference_torchmetrics()
    if tm is None:
        pytest.skip("oracle unavailable")
    import torch

    from torchmetrics_tpu.detection import ModifiedPanopticQuality, PanopticQuality

    if variant == "mpq" and flags:
        pytest.skip("reference ModifiedPanopticQuality has no return flags")
    things, stuffs, batches = _panoptic_batches()
    if variant == "pq":
        ours = PanopticQuality(things=things, stuffs=stuffs, **flags)
        ref = tm.detection.PanopticQuality(things=things, stuffs=stuffs, **flags)
    else:
        ours = ModifiedPanopticQuality(things=things, stuffs=stuffs)
        ref = tm.detection.ModifiedPanopticQuality(things=things, stuffs=stuffs)
    for preds, target in batches:
        ours.update(jnp.asarray(preds), jnp.asarray(target))
        ref.update(torch.as_tensor(preds), torch.as_tensor(target))
    _assert_allclose(ours.compute(), ref.compute().numpy(), atol=1e-5)


def test_panoptic_functional_matches_class():
    from torchmetrics_tpu.functional.detection import panoptic_quality

    things, stuffs, batches = _panoptic_batches(num_updates=1)
    preds, target = batches[0]
    from torchmetrics_tpu.detection import PanopticQuality

    m = PanopticQuality(things=things, stuffs=stuffs)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    _assert_allclose(panoptic_quality(jnp.asarray(preds), jnp.asarray(target), things, stuffs), m.compute(), atol=1e-6)


def test_panoptic_merge_matches_single():
    from torchmetrics_tpu.detection import PanopticQuality

    things, stuffs, batches = _panoptic_batches(num_updates=3)
    single = PanopticQuality(things=things, stuffs=stuffs)
    shards = [PanopticQuality(things=things, stuffs=stuffs) for _ in range(3)]
    for (preds, target), shard in zip(batches, shards):
        single.update(jnp.asarray(preds), jnp.asarray(target))
        shard.update(jnp.asarray(preds), jnp.asarray(target))
    merged = shards[0]
    merged.merge_state(shards[1])
    merged.merge_state(shards[2])
    _assert_allclose(merged.compute(), single.compute(), atol=1e-6)


def test_panoptic_validation_errors():
    from torchmetrics_tpu.detection import PanopticQuality

    with pytest.raises(ValueError, match="distinct"):
        PanopticQuality(things={0, 1}, stuffs={1, 2})
    with pytest.raises(ValueError, match="non-empty"):
        PanopticQuality(things=set(), stuffs=set())
    m = PanopticQuality(things={0}, stuffs={6})
    with pytest.raises(ValueError, match="Unknown categories"):
        m.update(jnp.asarray(np.full((1, 2, 2, 2), 3, np.int32)), jnp.asarray(np.zeros((1, 2, 2, 2), np.int32)))


def test_panoptic_negative_instance_ids():
    """Regression: negative instance sentinels must not shift categories in the
    int64 color encoding."""
    from torchmetrics_tpu.detection import PanopticQuality

    cat = np.array([[[0, 1], [6, 0]]], np.int64)  # (1, 2, 2) cats
    inst = np.array([[[-1, 2], [5, -1]]], np.int64)
    arr = np.stack([cat, inst], axis=-1)
    m = PanopticQuality(things={0, 1}, stuffs={6})
    m.update(jnp.asarray(arr), jnp.asarray(arr))  # exact match => PQ 1.0
    assert float(m.compute()) == pytest.approx(1.0)
