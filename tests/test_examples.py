"""The example scripts must stay runnable (VERDICT r2 #9: examples run in CI)."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

_EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


@pytest.mark.parametrize("script", ["pjit_eval_loop.py", "fid_clipscore_custom_extractor.py", "checkpoint_resume.py"])
def test_example_runs(script):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.join(_EXAMPLES, "..") + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, script)],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert res.stdout.strip(), "example should print results"
