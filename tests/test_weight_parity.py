"""Weight-converter + architecture parity for the model-backed image metrics.

torchvision / torch-fidelity are not installed, so each test builds a from-scratch
torch twin with torchvision's exact module naming, randomizes its weights (and BN
statistics), runs the in-tree converter on its ``state_dict()``, and checks the jnp
network reproduces the torch forward. This proves the conversion path end to end:
any weights in the torchvision layout — including the real pretrained ones —
convert correctly. The real trained calibration weights the reference ships
in-tree (``lpips_models/alex.pth`` lin heads, ``dists_models/weights.pt``
alpha/beta) are used directly where they exist.
"""

from __future__ import annotations

import os
import pickle

import numpy as np
import pytest
import torch
from torch import nn
from torch.nn import functional as tF

_REF_LPIPS_ALEX = "/root/reference/src/torchmetrics/functional/image/lpips_models/alex.pth"
_REF_DISTS = "/root/reference/src/torchmetrics/functional/image/dists_models/weights.pt"


def _randomize_bn(model: nn.Module, seed: int = 0) -> None:
    g = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, nn.BatchNorm2d):
                m.running_mean.normal_(0, 0.5, generator=g)
                m.running_var.uniform_(0.5, 2.0, generator=g)


# --------------------------------------------------------------------- LPIPS -----

def _alex_features():
    return nn.Sequential(
        nn.Conv2d(3, 64, 11, 4, 2), nn.ReLU(True), nn.MaxPool2d(3, 2),
        nn.Conv2d(64, 192, 5, 1, 2), nn.ReLU(True), nn.MaxPool2d(3, 2),
        nn.Conv2d(192, 384, 3, 1, 1), nn.ReLU(True),
        nn.Conv2d(384, 256, 3, 1, 1), nn.ReLU(True),
        nn.Conv2d(256, 256, 3, 1, 1), nn.ReLU(True), nn.MaxPool2d(3, 2),
    )


def _vgg16_features():
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512, "M"]
    layers, c_in = [], 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2d(2, 2))
        else:
            layers += [nn.Conv2d(c_in, v, 3, 1, 1), nn.ReLU(True)]
            c_in = v
    return nn.Sequential(*layers)


class _Fire(nn.Module):
    def __init__(self, c_in, sq, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2d(c_in, sq, 1)
        self.squeeze_activation = nn.ReLU(True)
        self.expand1x1 = nn.Conv2d(sq, e1, 1)
        self.expand1x1_activation = nn.ReLU(True)
        self.expand3x3 = nn.Conv2d(sq, e3, 3, padding=1)
        self.expand3x3_activation = nn.ReLU(True)

    def forward(self, x):
        x = self.squeeze_activation(self.squeeze(x))
        return torch.cat(
            [self.expand1x1_activation(self.expand1x1(x)), self.expand3x3_activation(self.expand3x3(x))], 1
        )


def _squeeze_features():
    return nn.Sequential(
        nn.Conv2d(3, 64, 3, 2), nn.ReLU(True), nn.MaxPool2d(3, 2, ceil_mode=True),
        _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64), nn.MaxPool2d(3, 2, ceil_mode=True),
        _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128), nn.MaxPool2d(3, 2, ceil_mode=True),
        _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
        _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
    )


_LPIPS_NETS = {
    "alex": (_alex_features, (2, 5, 8, 10, 12), (64, 192, 384, 256, 256)),
    "vgg": (_vgg16_features, (4, 9, 16, 23, 30), (64, 128, 256, 512, 512)),
    "squeeze": (_squeeze_features, (2, 5, 8, 10, 11, 12, 13), (64, 128, 256, 384, 384, 512, 512)),
}


def _torch_lpips(features, taps, lin_ws, x0, x1):
    """Reference LPIPS forward (functional/image/lpips.py): scaling layer, tapped
    relu features, channel-unit-norm, squared diff, 1x1 lin heads, spatial mean."""
    shift = torch.tensor([-0.030, -0.088, -0.188]).view(1, 3, 1, 1)
    scale = torch.tensor([0.458, 0.448, 0.450]).view(1, 3, 1, 1)

    def feats(x):
        h = (x - shift) / scale
        outs = []
        for i, mod in enumerate(features):
            h = mod(h)
            if i + 1 in taps:
                outs.append(h)
        return outs

    def unit_norm(f):
        return f / torch.sqrt(1e-8 + (f**2).sum(1, keepdim=True))

    total = torch.zeros(x0.shape[0])
    with torch.no_grad():
        for f0, f1, lw in zip(feats(x0), feats(x1), lin_ws):
            diff = (unit_norm(f0) - unit_norm(f1)) ** 2
            total = total + tF.conv2d(diff, lw).mean(dim=(2, 3))[:, 0]
    return total.numpy()


@pytest.mark.parametrize("net_type", ["alex", "vgg", "squeeze"])
def test_lpips_converter_parity(net_type, tmp_path):
    from torchmetrics_tpu.functional.image.lpips import LPIPSNetwork, convert_lpips_weights

    make, taps, chns = _LPIPS_NETS[net_type]
    torch.manual_seed(10)
    features = make().eval()
    if net_type == "alex" and os.path.exists(_REF_LPIPS_ALEX):
        # the REAL trained calibration heads the reference ships in-tree
        lin_sd = torch.load(_REF_LPIPS_ALEX, map_location="cpu", weights_only=True)
    else:
        lin_sd = {
            f"lin{i}.model.1.weight": torch.rand(1, c, 1, 1) * 0.1 for i, c in enumerate(chns)
        }
    lin_ws = [lin_sd[f"lin{i}.model.1.weight"] for i in range(len(chns))]

    rng = np.random.default_rng(11)
    x0 = torch.as_tensor(rng.uniform(-1, 1, (2, 3, 64, 64)).astype(np.float32))
    x1 = torch.as_tensor(rng.uniform(-1, 1, (2, 3, 64, 64)).astype(np.float32))
    want = _torch_lpips(features, taps, lin_ws, x0, x1)

    out = tmp_path / f"lpips_{net_type}.pkl"
    convert_lpips_weights(features.state_dict(), lin_sd, net_type, str(out))
    net = LPIPSNetwork(net_type, pretrained=True, weights_path=str(out))
    got = np.asarray(net(x0.numpy(), x1.numpy()))
    np.testing.assert_allclose(got, want, atol=1e-4)


# --------------------------------------------------------------------- DISTS -----

class _L2Pool(nn.Module):
    """Reference L2pooling (dists.py:56-75)."""

    def __init__(self, channels, filter_size=5, stride=2):
        super().__init__()
        self.padding = (filter_size - 2) // 2
        self.stride = stride
        a = np.hanning(filter_size)[1:-1]
        g = torch.as_tensor((a[:, None] * a[None, :]) / (a[:, None] * a[None, :]).sum(), dtype=torch.float32)
        self.register_buffer("filter", g[None, None].repeat(channels, 1, 1, 1))

    def forward(self, x):
        out = tF.conv2d(x**2, self.filter, stride=self.stride, padding=self.padding, groups=x.shape[1])
        return (out + 1e-12).sqrt()


def test_dists_converter_parity(tmp_path):
    from torchmetrics_tpu.functional.image.dists import DISTSNetwork, convert_dists_weights

    torch.manual_seed(12)
    vgg = _vgg16_features().eval()
    if os.path.exists(_REF_DISTS):
        dists_sd = torch.load(_REF_DISTS, map_location="cpu", weights_only=True)  # real alpha/beta
    else:
        dists_sd = {"alpha": torch.rand(1, 1475, 1, 1) * 0.1, "beta": torch.rand(1, 1475, 1, 1) * 0.1}
    alpha, beta = dists_sd["alpha"], dists_sd["beta"]

    # reference stage structure: maxpools swapped for L2pool at indices 4/9/16/23
    stages = []
    mods = list(vgg)
    bounds = [(0, 4), (5, 9), (10, 16), (17, 23), (24, 30)]
    pool_ch = [64, 128, 256, 512]
    for si, (lo, hi) in enumerate(bounds):
        seq = []
        if si > 0:
            seq.append(_L2Pool(pool_ch[si - 1]))
        seq += mods[lo:hi]
        stages.append(nn.Sequential(*seq))

    mean = torch.tensor([0.485, 0.456, 0.406]).view(1, 3, 1, 1)
    std = torch.tensor([0.229, 0.224, 0.225]).view(1, 3, 1, 1)

    def torch_dists(x, y):
        def feats(v):
            h = (v - mean) / std
            outs = [v]
            for stage in stages:
                h = stage(h)
                outs.append(h)
            return outs

        with torch.no_grad():
            f0, f1 = feats(x), feats(y)
            chns = [3, 64, 128, 256, 512, 512]
            a_split = torch.split(alpha / (alpha.sum() + beta.sum()), chns, dim=1)
            b_split = torch.split(beta / (alpha.sum() + beta.sum()), chns, dim=1)
            c1 = c2 = 1e-6
            d1 = torch.zeros(x.shape[0])
            d2 = torch.zeros(x.shape[0])
            for k in range(len(chns)):
                xm = f0[k].mean([2, 3], keepdim=True)
                ym = f1[k].mean([2, 3], keepdim=True)
                s1 = (2 * xm * ym + c1) / (xm**2 + ym**2 + c1)
                d1 = d1 + (a_split[k] * s1).sum(1).flatten()
                xv = ((f0[k] - xm) ** 2).mean([2, 3], keepdim=True)
                yv = ((f1[k] - ym) ** 2).mean([2, 3], keepdim=True)
                cov = (f0[k] * f1[k]).mean([2, 3], keepdim=True) - xm * ym
                s2 = (2 * cov + c2) / (xv + yv + c2)
                d2 = d2 + (b_split[k] * s2).sum(1).flatten()
        return (1 - (d1 + d2)).numpy()

    rng = np.random.default_rng(13)
    x = torch.as_tensor(rng.random((2, 3, 64, 64)).astype(np.float32))
    y = torch.as_tensor(rng.random((2, 3, 64, 64)).astype(np.float32))
    want = torch_dists(x, y)

    out = tmp_path / "dists.pkl"
    convert_dists_weights(vgg.state_dict(), dists_sd, str(out))
    net = DISTSNetwork(pretrained=True, weights_path=str(out))
    got = np.asarray(net(x.numpy(), y.numpy()))
    np.testing.assert_allclose(got, want, atol=1e-4)


# ----------------------------------------------------------------- Inception -----

class _BasicConv2d(nn.Module):
    def __init__(self, c_in, c_out, **kwargs):
        super().__init__()
        self.conv = nn.Conv2d(c_in, c_out, bias=False, **kwargs)
        self.bn = nn.BatchNorm2d(c_out, eps=0.001)

    def forward(self, x):
        return tF.relu(self.bn(self.conv(x)), inplace=True)


class _IncA(nn.Module):
    def __init__(self, c_in, pool_features):
        super().__init__()
        self.branch1x1 = _BasicConv2d(c_in, 64, kernel_size=1)
        self.branch5x5_1 = _BasicConv2d(c_in, 48, kernel_size=1)
        self.branch5x5_2 = _BasicConv2d(48, 64, kernel_size=5, padding=2)
        self.branch3x3dbl_1 = _BasicConv2d(c_in, 64, kernel_size=1)
        self.branch3x3dbl_2 = _BasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = _BasicConv2d(96, 96, kernel_size=3, padding=1)
        self.branch_pool = _BasicConv2d(c_in, pool_features, kernel_size=1)

    def forward(self, x):
        return torch.cat([
            self.branch1x1(x),
            self.branch5x5_2(self.branch5x5_1(x)),
            self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x))),
            self.branch_pool(tF.avg_pool2d(x, 3, 1, 1)),
        ], 1)


class _IncB(nn.Module):
    def __init__(self, c_in):
        super().__init__()
        self.branch3x3 = _BasicConv2d(c_in, 384, kernel_size=3, stride=2)
        self.branch3x3dbl_1 = _BasicConv2d(c_in, 64, kernel_size=1)
        self.branch3x3dbl_2 = _BasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = _BasicConv2d(96, 96, kernel_size=3, stride=2)

    def forward(self, x):
        return torch.cat([
            self.branch3x3(x),
            self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x))),
            tF.max_pool2d(x, 3, 2),
        ], 1)


class _IncC(nn.Module):
    def __init__(self, c_in, c7):
        super().__init__()
        self.branch1x1 = _BasicConv2d(c_in, 192, kernel_size=1)
        self.branch7x7_1 = _BasicConv2d(c_in, c7, kernel_size=1)
        self.branch7x7_2 = _BasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7_3 = _BasicConv2d(c7, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_1 = _BasicConv2d(c_in, c7, kernel_size=1)
        self.branch7x7dbl_2 = _BasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_3 = _BasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7dbl_4 = _BasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_5 = _BasicConv2d(c7, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch_pool = _BasicConv2d(c_in, 192, kernel_size=1)

    def forward(self, x):
        b7 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
        d = x
        for m in (self.branch7x7dbl_1, self.branch7x7dbl_2, self.branch7x7dbl_3,
                  self.branch7x7dbl_4, self.branch7x7dbl_5):
            d = m(d)
        return torch.cat([
            self.branch1x1(x), b7, d, self.branch_pool(tF.avg_pool2d(x, 3, 1, 1))
        ], 1)


class _IncD(nn.Module):
    def __init__(self, c_in):
        super().__init__()
        self.branch3x3_1 = _BasicConv2d(c_in, 192, kernel_size=1)
        self.branch3x3_2 = _BasicConv2d(192, 320, kernel_size=3, stride=2)
        self.branch7x7x3_1 = _BasicConv2d(c_in, 192, kernel_size=1)
        self.branch7x7x3_2 = _BasicConv2d(192, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7x3_3 = _BasicConv2d(192, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7x3_4 = _BasicConv2d(192, 192, kernel_size=3, stride=2)

    def forward(self, x):
        d = self.branch7x7x3_4(self.branch7x7x3_3(self.branch7x7x3_2(self.branch7x7x3_1(x))))
        return torch.cat([self.branch3x3_2(self.branch3x3_1(x)), d, tF.max_pool2d(x, 3, 2)], 1)


class _IncE(nn.Module):
    def __init__(self, c_in):
        super().__init__()
        self.branch1x1 = _BasicConv2d(c_in, 320, kernel_size=1)
        self.branch3x3_1 = _BasicConv2d(c_in, 384, kernel_size=1)
        self.branch3x3_2a = _BasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3_2b = _BasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = _BasicConv2d(c_in, 448, kernel_size=1)
        self.branch3x3dbl_2 = _BasicConv2d(448, 384, kernel_size=3, padding=1)
        self.branch3x3dbl_3a = _BasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3dbl_3b = _BasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch_pool = _BasicConv2d(c_in, 192, kernel_size=1)

    def forward(self, x):
        b3 = self.branch3x3_1(x)
        b3 = torch.cat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], 1)
        d = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
        d = torch.cat([self.branch3x3dbl_3a(d), self.branch3x3dbl_3b(d)], 1)
        return torch.cat([
            self.branch1x1(x), b3, d, self.branch_pool(tF.avg_pool2d(x, 3, 1, 1))
        ], 1)


class TorchInceptionV3(nn.Module):
    """torchvision ``inception_v3`` trunk (no aux, no fc), exact module naming."""

    def __init__(self):
        super().__init__()
        self.Conv2d_1a_3x3 = _BasicConv2d(3, 32, kernel_size=3, stride=2)
        self.Conv2d_2a_3x3 = _BasicConv2d(32, 32, kernel_size=3)
        self.Conv2d_2b_3x3 = _BasicConv2d(32, 64, kernel_size=3, padding=1)
        self.Conv2d_3b_1x1 = _BasicConv2d(64, 80, kernel_size=1)
        self.Conv2d_4a_3x3 = _BasicConv2d(80, 192, kernel_size=3)
        self.Mixed_5b = _IncA(192, 32)
        self.Mixed_5c = _IncA(256, 64)
        self.Mixed_5d = _IncA(288, 64)
        self.Mixed_6a = _IncB(288)
        self.Mixed_6b = _IncC(768, 128)
        self.Mixed_6c = _IncC(768, 160)
        self.Mixed_6d = _IncC(768, 160)
        self.Mixed_6e = _IncC(768, 192)
        self.Mixed_7a = _IncD(768)
        self.Mixed_7b = _IncE(1280)
        self.Mixed_7c = _IncE(2048)

    def forward(self, x):
        x = self.Conv2d_1a_3x3(x)
        x = self.Conv2d_2a_3x3(x)
        x = self.Conv2d_2b_3x3(x)
        x = tF.max_pool2d(x, 3, 2)
        x = self.Conv2d_3b_1x1(x)
        x = self.Conv2d_4a_3x3(x)
        x = tF.max_pool2d(x, 3, 2)
        for name in ("Mixed_5b", "Mixed_5c", "Mixed_5d", "Mixed_6a", "Mixed_6b",
                     "Mixed_6c", "Mixed_6d", "Mixed_6e", "Mixed_7a", "Mixed_7b", "Mixed_7c"):
            x = getattr(self, name)(x)
        return tF.adaptive_avg_pool2d(x, 1).flatten(1)


def test_inception_converter_parity(tmp_path):
    from torchmetrics_tpu.image._extractors import (
        InceptionV3Features,
        convert_torchvision_inception_weights,
    )

    torch.manual_seed(14)
    twin = TorchInceptionV3().eval()
    _randomize_bn(twin, seed=15)
    rng = np.random.default_rng(16)
    imgs = rng.random((2, 3, 299, 299)).astype(np.float32)
    with torch.no_grad():
        # the trunk mirrors torch-fidelity's (x - 128)/128 on 0-255 input
        # (reference image/fid.py:103); [0,1] floats are scaled by 255 on entry
        want = twin(torch.as_tensor((imgs * 255.0 - 128.0) / 128.0)).numpy()

    out = tmp_path / "inception.pkl"
    convert_torchvision_inception_weights(twin.state_dict(), str(out))
    extractor = InceptionV3Features(weights_path=str(out))
    got = np.asarray(extractor(imgs))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=1e-3)
