"""Durability & failover plane tests (serving/durability + degraded sync).
Marker ``durability``.

The load-bearing claims, each pinned:

- **crash consistency**: a snapshot is either bitwise what was written or
  refused — EVERY kill point (truncation at any byte), bitflip, and stale
  format version raises ``StateCorruptionError``, never a silent partial
  load, and the previous generation stays loadable;
- **the journal contract**: records survive segment rotation in strict seq
  order, a torn tail on the LAST segment is the bounded-loss crash window
  (tolerated), while a damaged complete record or a damaged earlier segment
  is corruption (raises);
- **restore + replay = the primary, bitwise**: a standby that restores the
  latest snapshot and replays the journal tail reaches the exact pre-crash
  engine state — replay is idempotent (seq dedup) and digest-verified;
- **degraded sync**: a rank lost mid-collective (``DeadRank``) folds over
  the survivor quorum (no hang, no zero-row fold), revival reconciles as a
  rejoin with no double-count, and the counters/events tell the story;
- **kill-and-failover soak**: the chaos plane's mid-run failover drill ends
  with zero unrecovered faults and both parity gates at 1.0.
"""

from __future__ import annotations

import json
import os
import struct
import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from torchmetrics_tpu.aggregation import SumMetric
from torchmetrics_tpu.classification import MulticlassAccuracy
from torchmetrics_tpu.observability import telemetry_session
from torchmetrics_tpu.parallel import AsyncSyncHandle, coalesce as C
from torchmetrics_tpu.reliability import DeadRank
from torchmetrics_tpu.serving import (
    ServingConfig,
    ServingEngine,
    SnapshotStore,
    TrafficJournal,
    batch_digest,
)
from torchmetrics_tpu.serving.durability import SNAPSHOT_MAGIC, _HEADER_LEN_FMT
from torchmetrics_tpu.utilities.exceptions import (
    StateCorruptionError,
    TorchMetricsUserError,
)

pytestmark = pytest.mark.durability

NUM_CLASSES = 3
BATCH = 4


@pytest.fixture(autouse=True)
def _clean_liveness():
    """The degraded-sync plane's tombstone table is process-global; isolate
    every test from a neighbour's dead ranks."""
    C.clear_dead_ranks()
    yield
    C.clear_dead_ranks()


def _acc():
    return MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)


def _batch(rng):
    return (
        jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)).astype(np.float32)),
        jnp.asarray(rng.integers(0, NUM_CLASSES, BATCH, dtype=np.int32)),
    )


# ------------------------------------------------------------ snapshot store


def _sections():
    return {
        "a/int": np.arange(12, dtype=np.int64).reshape(3, 4),
        "b/float": np.linspace(-1.0, 1.0, 7, dtype=np.float32),
        "c/empty": np.zeros((0, 2), dtype=np.float64),
    }


def test_snapshot_round_trip_and_generations(tmp_path):
    store = SnapshotStore(str(tmp_path))
    meta = {"applied_seq": 41, "note": "gen1"}
    out = store.write(meta, _sections())
    assert out["generation"] == 1 and out["bytes"] == os.path.getsize(out["path"])
    store.write({"note": "gen2"}, {"x": np.ones(3)})
    assert store.generations() == [1, 2]
    # latest by default
    m2, s2 = store.read()
    assert m2 == {"note": "gen2"} and list(s2) == ["x"]
    # an older generation stays addressable
    m1, s1 = store.read(generation=1)
    assert m1 == meta
    for name, want in _sections().items():
        np.testing.assert_array_equal(s1[name], want)
        assert s1[name].dtype == want.dtype


def test_snapshot_every_kill_point_refuses_to_load(tmp_path):
    """Truncation at EVERY byte offset of the container must raise
    ``StateCorruptionError`` — a torn snapshot never half-loads."""
    store = SnapshotStore(str(tmp_path / "src"))
    path = store.write({"k": 1}, _sections())["path"]
    raw = open(path, "rb").read()
    offsets = sorted(set(range(0, len(raw), 7)) | {0, 1, len(SNAPSHOT_MAGIC), len(raw) - 1})
    for i, cut in enumerate(offsets):
        victim = SnapshotStore(str(tmp_path / f"cut{i}"))
        with open(victim.path_for(1), "wb") as fh:
            fh.write(raw[:cut])
        with pytest.raises(StateCorruptionError):
            victim.read()


def test_snapshot_bitflip_and_stale_version_refuse_to_load(tmp_path):
    store = SnapshotStore(str(tmp_path / "src"))
    path = store.write({"k": 1}, _sections())["path"]
    raw = open(path, "rb").read()
    hoff = len(SNAPSHOT_MAGIC)
    (hlen,) = struct.unpack_from(_HEADER_LEN_FMT, raw, hoff)
    body_at = hoff + struct.calcsize(_HEADER_LEN_FMT) + hlen
    flips = {
        "magic": 0,
        "header": hoff + struct.calcsize(_HEADER_LEN_FMT) + hlen // 2,
        "payload": body_at + (len(raw) - body_at) // 2,
    }
    for i, (label, at) in enumerate(flips.items()):
        victim = SnapshotStore(str(tmp_path / f"flip-{label}"))
        damaged = bytearray(raw)
        damaged[at] ^= 0xFF
        with open(victim.path_for(1), "wb") as fh:
            fh.write(bytes(damaged))
        with pytest.raises(StateCorruptionError):
            victim.read()
    # a FUTURE format version is refused, not misdecoded
    header = json.loads(raw[hoff + struct.calcsize(_HEADER_LEN_FMT) : body_at])
    header["version"] = 99
    hb = json.dumps(header, sort_keys=True).encode("utf-8")
    stale = SNAPSHOT_MAGIC + struct.pack(_HEADER_LEN_FMT, len(hb)) + hb + raw[body_at:]
    victim = SnapshotStore(str(tmp_path / "stale"))
    with open(victim.path_for(1), "wb") as fh:
        fh.write(stale)
    with pytest.raises(StateCorruptionError, match="version"):
        victim.read()


def test_previous_generation_survives_a_torn_latest(tmp_path):
    store = SnapshotStore(str(tmp_path))
    store.write({"note": "good"}, _sections())
    p2 = store.write({"note": "torn"}, _sections())["path"]
    raw = open(p2, "rb").read()
    with open(p2, "wb") as fh:
        fh.write(raw[: len(raw) // 2])
    with pytest.raises(StateCorruptionError):
        store.read()  # latest is torn
    meta, _ = store.read(generation=1)  # explicit fallback stays intact
    assert meta == {"note": "good"}
    assert not any(".tmp-" in n for n in os.listdir(tmp_path))


def test_empty_store_is_a_user_error_not_corruption(tmp_path):
    with pytest.raises(TorchMetricsUserError, match="no snapshot generations"):
        SnapshotStore(str(tmp_path)).read()


# ---------------------------------------------------------------- journal


def test_journal_round_trip_rotation_and_fsync_batching(tmp_path):
    root = str(tmp_path)
    with TrafficJournal(root, fsync_every=2, segment_records=3) as j:
        for seq in range(1, 9):
            j.append(f"tenant-{seq % 3}", f"d{seq}", seq, t=seq * 0.5)
    assert j.records == 8
    # fsync batching: 4 size-2 batches; rotation/close flushes ride the same path
    assert j.fsyncs >= 4
    segs = [n for n in os.listdir(root) if n.startswith("seg-")]
    assert len(segs) >= 3  # 8 records at 3/segment rotated at least twice
    recs = TrafficJournal.read(root)
    assert [r.seq for r in recs] == list(range(1, 9))
    assert recs[0].tenant_id == "tenant-1" and recs[0].digest == "d1"
    assert recs[3].t == 2.0
    # a fresh instance opens a NEW segment and appends after history
    with TrafficJournal(root) as j2:
        j2.append(7, "d9", 9)
    recs = TrafficJournal.read(root)
    assert [r.seq for r in recs] == list(range(1, 10))
    assert recs[-1].tenant_id == 7  # int ids round-trip as ints


def test_journal_torn_tail_tolerated_corruption_raises(tmp_path):
    root = str(tmp_path)
    with TrafficJournal(root, segment_records=4) as j:
        for seq in range(1, 7):
            j.append("t", f"d{seq}", seq)
    segs = sorted(n for n in os.listdir(root) if n.startswith("seg-"))
    last = os.path.join(root, segs[-1])
    raw = open(last, "rb").read()
    # torn tail on the FINAL segment: bounded loss, reads the intact prefix
    with open(last, "wb") as fh:
        fh.write(raw[:-5])
    recs = TrafficJournal.read(root)
    assert [r.seq for r in recs] == [1, 2, 3, 4, 5]
    # a COMPLETE record with a flipped body byte is corruption, not a tail
    first = os.path.join(root, segs[0])
    raw0 = bytearray(open(first, "rb").read())
    raw0[-3] ^= 0x01
    with open(first, "wb") as fh:
        fh.write(bytes(raw0))
    with pytest.raises(StateCorruptionError, match="CRC"):
        TrafficJournal.read(root)


def test_journal_damage_to_a_rotated_segment_raises(tmp_path):
    root = str(tmp_path)
    with TrafficJournal(root, segment_records=2) as j:
        for seq in range(1, 6):
            j.append("t", f"d{seq}", seq)
    segs = sorted(n for n in os.listdir(root) if n.startswith("seg-"))
    assert len(segs) >= 2
    first = os.path.join(root, segs[0])
    raw = open(first, "rb").read()
    with open(first, "wb") as fh:
        fh.write(raw[:-4])  # truncation NOT on the final segment
    with pytest.raises(StateCorruptionError, match="truncated"):
        TrafficJournal.read(root)


def test_journal_sequence_regression_raises(tmp_path):
    root = str(tmp_path)
    with TrafficJournal(root) as j:
        j.append("t", "d5", 5)
        j.append("t", "d3", 3)
    with pytest.raises(StateCorruptionError, match="regressed"):
        TrafficJournal.read(root)


def test_journal_validates_and_reads_missing_root_as_empty(tmp_path):
    with pytest.raises(TorchMetricsUserError, match="fsync_every"):
        TrafficJournal(str(tmp_path), fsync_every=0)
    assert TrafficJournal.read(str(tmp_path / "never-created")) == []


def test_batch_digest_is_content_addressed():
    rng = np.random.default_rng(3)
    preds, target = _batch(rng)
    base = batch_digest((preds, target), {})
    assert base == batch_digest((jnp.asarray(np.asarray(preds)), target), {})
    bumped = preds.at[0, 0].add(1.0)
    assert batch_digest((bumped, target), {}) != base
    assert batch_digest((preds, target.astype(jnp.float32)), {}) != base
    assert batch_digest((preds[:2], target[:2]), {}) != base


# ----------------------------------------------- engine snapshot + replay


def _config(root, **kw):
    kw.setdefault("capacity", 6)
    kw.setdefault("megabatch_size", 3)
    return ServingConfig(journal=os.path.join(root, "journal"), **kw)


def test_engine_restore_plus_replay_reaches_bitwise_parity(tmp_path):
    """The headline recovery contract: kill the primary after a snapshot and
    more journaled traffic — restore + replay on a cold standby reproduces
    every tenant's state bit for bit."""
    root = str(tmp_path)
    snap_dir = os.path.join(root, "snaps")
    rng = np.random.default_rng(17)
    tenants = [f"t{i}" for i in range(8)]  # 8 tenants, capacity 6: spill in play
    primary = ServingEngine(_acc(), _config(root))
    retained = {}
    for step in range(30):
        b = _batch(rng)
        assert primary.update(tenants[step % len(tenants)], *b)
        retained[primary._applied_seq] = ((b[0], b[1]), {})
        if step == 14:
            out = primary.snapshot(snap_dir)
            assert out["generation"] == 1 and out["tenants"] == len(tenants)
    primary.flush()
    want = {tid: primary.state_dict(tid) for tid in tenants}
    want_vals = {tid: float(primary.compute(tid)) for tid in tenants}
    primary.close()  # the kill point — journal tail is on disk

    standby = ServingEngine(_acc(), _config(root))
    standby.restore(snap_dir)
    records = TrafficJournal.read(os.path.join(root, "journal"))
    replayed = standby.replay_journal(records, lambda r: retained[r.seq])
    assert replayed == 30 - 15  # everything after the snapshot, exactly once
    standby.flush()
    for tid in tenants:
        got = standby.state_dict(tid)
        assert sorted(got) == sorted(want[tid])
        for name, v in want[tid].items():
            np.testing.assert_array_equal(np.asarray(got[name]), np.asarray(v), err_msg=f"{tid}/{name}")
        assert float(standby.compute(tid)) == want_vals[tid]
    # replay is idempotent: a retry applies nothing
    assert standby.replay_journal(records, lambda r: retained[r.seq]) == 0
    standby.close()


def test_replay_verifies_digests_and_restore_checks_geometry(tmp_path):
    root = str(tmp_path)
    snap_dir = os.path.join(root, "snaps")
    rng = np.random.default_rng(5)
    engine = ServingEngine(_acc(), _config(root))
    retained = {}
    for _ in range(4):
        b = _batch(rng)
        engine.update("solo", *b)
        retained[engine._applied_seq] = ((b[0], b[1]), {})
    engine.snapshot(snap_dir)
    engine.close()
    # geometry mismatch: refuse before touching any state
    other = ServingEngine(_acc(), ServingConfig(capacity=8, megabatch_size=4))
    with pytest.raises(TorchMetricsUserError, match="geometry"):
        other.restore(snap_dir)
    # a retention buffer that diverged from what the primary admitted
    standby = ServingEngine(_acc(), _config(root))
    records = TrafficJournal.read(os.path.join(root, "journal"))
    wrong = _batch(np.random.default_rng(99))
    standby._applied_seq = 0  # force every record through the digest check
    with pytest.raises(StateCorruptionError, match="digest"):
        standby.replay_journal(records, lambda r: ((wrong[0], wrong[1]), {}))
    standby.close()


def test_journal_requires_json_safe_tenant_ids(tmp_path):
    engine = ServingEngine(_acc(), _config(str(tmp_path)))
    rng = np.random.default_rng(0)
    with pytest.raises(TorchMetricsUserError, match="tenant ids"):
        engine.update(("tuple", "id"), *_batch(rng))
    engine.close()


# ------------------------------------------------------------ degraded sync


def test_dead_rank_survivor_quorum_then_rejoin():
    """World of 2, rank 1 dead: the coalesced sync folds the survivor only
    (no hang, no zero-row fold), marks itself degraded, and the revival sync
    reconciles the rejoin — folding the returned rank exactly once."""
    dead = DeadRank(world=2, rank=1)
    m = SumMetric(dist_sync_fn=dead, distributed_available_fn=lambda: True)
    m.update(jnp.asarray(3.0))
    with telemetry_session() as rec:
        m.sync()
        assert float(m.sum_value) == 3.0  # survivor quorum: local only
        m.unsync()
        assert C.dead_ranks() == {1: 1}
        dead.revive()
        m.sync()  # the revival sync IS the rejoin reconciliation
        assert float(m.sum_value) == 6.0  # rejoined mirror folds once
        m.unsync()
        assert C.dead_ranks() == {}
    assert rec.counters.value("degraded_syncs") >= 1
    assert rec.counters.value("rank_rejoins") >= 1
    kinds = {e.kind for e in rec.events_of("degraded_sync", "rank_rejoin")}
    assert kinds == {"degraded_sync", "rank_rejoin"}


def test_dead_rank_validates():
    with pytest.raises(ValueError, match="world"):
        DeadRank(world=1)
    with pytest.raises(ValueError, match="rank"):
        DeadRank(world=2, rank=2)


def test_async_handle_reports_degraded_world():
    dead = DeadRank(world=2, rank=1)
    handle = AsyncSyncHandle([{"sum_value": jnp.asarray(4.0)}], [{"sum_value": "sum"}], dist_sync_fn=dead)
    (synced,) = handle.commit()
    assert float(synced["sum_value"]) == 4.0
    assert handle.degraded and handle.dead_ranks == C.dead_ranks() != {}
    dead.revive()
    handle = AsyncSyncHandle([{"sum_value": jnp.asarray(4.0)}], [{"sum_value": "sum"}], dist_sync_fn=dead)
    (synced,) = handle.commit()
    assert float(synced["sum_value"]) == 8.0
    assert not handle.degraded and handle.dead_ranks == {}


def test_liveness_epoch_bumps_monotonically():
    e0 = C.liveness_epoch()
    assert C.bump_liveness_epoch() == e0 + 1
    assert C.liveness_epoch() == e0 + 1


# --------------------------------------------------- kill-and-failover soak


def test_durable_failover_soak_parity(tmp_path):
    """The acceptance drill: a seeded soak with rank_loss + coordination_outage
    scheduled AND a mid-run kill-and-failover — zero unrecovered faults, exact
    reconciliation, both parity gates at 1.0, RPO zero at fsync_every=1."""
    from torchmetrics_tpu.chaos import SoakConfig, TrafficConfig, run_soak

    cfg = SoakConfig(
        traffic=TrafficConfig(
            seed=7, tenants=12, steps=40, base_rate=3.0, churn_every=14, churn_count=3
        ),
        capacity=6,
        megabatch_size=3,
        sync_every=10,
        max_tenants_per_sec=30.0,
        spill_codec="int8",
        sync_codec="bf16",
        durability_dir=str(tmp_path),
        snapshot_every=12,
        failover_at=26,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r = run_soak(cfg)
    assert r.counters["unrecovered_faults"] == 0
    assert r.reconciliation["exact"]
    assert r.counters["failovers"] == 1
    assert r.counters["failover_state_parity"] == 1.0
    assert r.counters["degraded_sync_parity"] == 1.0
    assert r.counters["failover_rpo_records"] == 0
    assert r.counters["snapshots"] >= 2 and r.counters["snapshot_restores"] == 1
    assert r.counters["replayed_records"] > 0
    assert r.counters["journal_records"] == r.counters["journal_fsyncs"] > 0
    assert r.counters["degraded_syncs"] >= 1 and r.counters["rank_rejoins"] >= 1
    assert r.timing["failover_rto_ms"] > 0.0
    outcomes = {rec["kind"]: rec["outcome"] for rec in r.faults}
    assert outcomes["rank_loss"] == "recovered"
    assert outcomes["coordination_outage"] == "recovered"


def test_quarantine_transition_survives_failover_replay(tmp_path):
    """A quarantine AFTER the last snapshot must come back on the standby:
    the WAL journals the transition (error text + the rolled-back admission
    seqs) and replay re-applies the flag while skipping the folds the primary
    rolled back. Without the record, a standby replaying the fault-free
    journal would fold the very batch the primary refused and come up with
    the tenant live — state divergence (the regression this pins)."""
    root = str(tmp_path)
    snap_dir = os.path.join(root, "snaps")
    rng = np.random.default_rng(11)
    tenants = [f"t{i}" for i in range(6)]
    primary = ServingEngine(_acc(), _config(root, capacity=4, on_error="quarantine"))
    poison = {"armed": False}

    def hook(tids):
        if poison["armed"] and "t3" in tids:
            raise RuntimeError("injected poison for t3")

    primary._fault_hook = hook
    retained = {}
    for i in range(40):
        tid = tenants[i % len(tenants)]
        if tid == "t3" and primary.tenants().get("t3", {}).get("quarantined"):
            continue  # the primary refuses a quarantined tenant's traffic
        b = _batch(rng)
        assert primary.update(tid, *b)
        retained[primary._applied_seq] = ((b[0], b[1]), {})
        if i == 14:
            primary.snapshot(snap_dir)
        if i == 16:
            poison["armed"] = True  # quarantine lands INSIDE the replay window
        if i == 22:
            poison["armed"] = False
    primary.flush()
    info_p = primary.tenants()
    assert info_p["t3"]["quarantined"]
    err_p = primary._tenants["t3"].error
    live = [t for t in tenants if not info_p[t]["quarantined"]]
    want = {t: {k: np.asarray(v) for k, v in primary.state_dict(t).items()} for t in live}
    primary.close()

    records = TrafficJournal.read(os.path.join(root, "journal"))
    quar = [r for r in records if r.kind == "quarantine"]
    assert len(quar) == 1 and quar[0].tenant_id == "t3" and quar[0].rolled_back

    standby = ServingEngine(_acc(), _config(root, capacity=4, on_error="quarantine"))
    standby.restore(snap_dir)
    replayed = standby.replay_journal(records, lambda r: retained[r.seq])
    assert replayed > 0
    standby.flush()
    info_s = standby.tenants()
    assert info_s["t3"]["quarantined"]
    assert info_s["t3"]["update_count"] == info_p["t3"]["update_count"]
    assert standby._tenants["t3"].error == err_p
    assert standby.stats["quarantined"] == 1
    for t in live:
        assert info_s[t]["update_count"] == info_p[t]["update_count"]
        got = standby.state_dict(t)
        for name, v in want[t].items():
            np.testing.assert_array_equal(np.asarray(got[name]), v, err_msg=f"{t}/{name}")
    # idempotent: a retried replay applies nothing, quarantine included
    assert standby.replay_journal(records, lambda r: retained[r.seq]) == 0
    standby.close()


def test_soak_parity_with_quarantine_in_replay_window(tmp_path):
    """The CLI config that first exposed the missing quarantine record: the
    tenant_fault quarantine (step 12) lands between the last snapshot (step
    10) and the kill point (step 16), so the standby can only reach parity by
    honoring the journaled transition — and must report the quarantine it
    inherited, not resurrect the tenant."""
    from torchmetrics_tpu.chaos import SoakConfig, TrafficConfig, run_soak

    cfg = SoakConfig(
        traffic=TrafficConfig(seed=3, tenants=8, steps=30),
        capacity=6,
        megabatch_size=3,
        spill_codec="int8",
        max_tenants_per_sec=40.0,
        durability_dir=str(tmp_path),
        snapshot_every=10,
        failover_at=16,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r = run_soak(cfg)
    assert r.counters["unrecovered_faults"] == 0
    assert r.reconciliation["exact"]
    assert r.counters["failovers"] == 1
    assert r.counters["failover_state_parity"] == 1.0
    assert r.counters["degraded_sync_parity"] == 1.0
    assert r.counters["failover_rpo_records"] == 0
    # the standby carries the primary's quarantine across the failover
    assert r.counters["quarantined_faults"] == 1
    outcomes = {rec["kind"]: rec["outcome"] for rec in r.faults}
    assert outcomes["tenant_fault"] == "quarantined"
