"""Curve family (PR curve / ROC / AUROC / AP) vs sklearn.

Exact mode (thresholds=None) checked strictly against sklearn; binned mode checked
against exact mode within binning tolerance and for internal consistency (reference
tests/unittests/classification/test_precision_recall_curve.py, test_auroc.py)."""

from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn import metrics as sk

import torchmetrics_tpu.functional as F
from torchmetrics_tpu.classification import (
    BinaryAUROC,
    BinaryAveragePrecision,
    BinaryPrecisionRecallCurve,
    BinaryROC,
    MulticlassAUROC,
    MulticlassAveragePrecision,
    MultilabelAUROC,
)
from conftest import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES, seed_all
from helpers import MetricTester, _assert_allclose

_rng = seed_all(31)
_bin_preds = _rng.random((NUM_BATCHES, BATCH_SIZE)).astype(np.float32)
_bin_target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE))
_mc_scores = _rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32)
_mc_scores /= _mc_scores.sum(-1, keepdims=True)
_mc_target = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
_ml_target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))

_bp = np.concatenate(list(_bin_preds))
_bt = np.concatenate(list(_bin_target))
_mp = np.concatenate(list(_mc_scores))
_mt = np.concatenate(list(_mc_target))
_mlt = np.concatenate(list(_ml_target))


def test_binary_pr_curve_exact_vs_sklearn():
    p, r, t = F.binary_precision_recall_curve(jnp.asarray(_bp), jnp.asarray(_bt), thresholds=None)
    skp, skr, skt = sk.precision_recall_curve(_bt, _bp)
    np.testing.assert_allclose(np.asarray(p), skp, atol=1e-6)
    np.testing.assert_allclose(np.asarray(r), skr, atol=1e-6)
    np.testing.assert_allclose(np.asarray(t), skt, atol=1e-6)


def test_binary_roc_exact_vs_sklearn():
    fpr, tpr, _ = F.binary_roc(jnp.asarray(_bp), jnp.asarray(_bt), thresholds=None)
    skfpr, sktpr, _ = sk.roc_curve(_bt, _bp, drop_intermediate=False)
    np.testing.assert_allclose(np.asarray(fpr), skfpr, atol=1e-6)
    np.testing.assert_allclose(np.asarray(tpr), sktpr, atol=1e-6)


def test_binary_auroc_exact_vs_sklearn():
    ours = float(F.binary_auroc(jnp.asarray(_bp), jnp.asarray(_bt), thresholds=None))
    ref = sk.roc_auc_score(_bt, _bp)
    assert ours == pytest.approx(ref, abs=1e-6)


def test_binary_auroc_max_fpr():
    ours = float(F.binary_auroc(jnp.asarray(_bp), jnp.asarray(_bt), max_fpr=0.3, thresholds=None))
    ref = sk.roc_auc_score(_bt, _bp, max_fpr=0.3)
    assert ours == pytest.approx(ref, abs=1e-5)


def test_binary_average_precision_exact_vs_sklearn():
    ours = float(F.binary_average_precision(jnp.asarray(_bp), jnp.asarray(_bt), thresholds=None))
    ref = sk.average_precision_score(_bt, _bp)
    assert ours == pytest.approx(ref, abs=1e-6)


def test_multiclass_auroc_exact_vs_sklearn():
    for average, sk_avg in [("macro", "macro"), ("weighted", "weighted")]:
        ours = float(
            F.multiclass_auroc(jnp.asarray(_mp), jnp.asarray(_mt), num_classes=NUM_CLASSES, average=average, thresholds=None)
        )
        ref = sk.roc_auc_score(_mt, _mp, multi_class="ovr", average=sk_avg, labels=list(range(NUM_CLASSES)))
        assert ours == pytest.approx(ref, abs=1e-6), average


def test_multiclass_average_precision_exact_vs_sklearn():
    ours = np.asarray(
        F.multiclass_average_precision(jnp.asarray(_mp), jnp.asarray(_mt), num_classes=NUM_CLASSES, average=None, thresholds=None)
    )
    t_oh = np.eye(NUM_CLASSES)[_mt]
    ref = np.array([sk.average_precision_score(t_oh[:, c], _mp[:, c]) for c in range(NUM_CLASSES)])
    np.testing.assert_allclose(ours, ref, atol=1e-6)


def test_multilabel_auroc_exact_vs_sklearn():
    ours = float(
        F.multilabel_auroc(jnp.asarray(_mp), jnp.asarray(_mlt.reshape(-1, NUM_CLASSES)[: _mp.shape[0]]), num_labels=NUM_CLASSES, average="macro", thresholds=None)
    )
    ref = sk.roc_auc_score(_mlt.reshape(-1, NUM_CLASSES)[: _mp.shape[0]], _mp, average="macro")
    assert ours == pytest.approx(ref, abs=1e-6)


@pytest.mark.parametrize("thresholds", [None, 200])
def test_binary_auroc_class_stateful(thresholds):
    metric = BinaryAUROC(thresholds=thresholds)
    for i in range(NUM_BATCHES):
        metric.update(jnp.asarray(_bin_preds[i]), jnp.asarray(_bin_target[i]))
    ours = float(metric.compute())
    ref = sk.roc_auc_score(_bt, _bp)
    tol = 1e-6 if thresholds is None else 0.02
    assert ours == pytest.approx(ref, abs=tol)


def test_binned_matches_exact_closely():
    exact = float(F.binary_average_precision(jnp.asarray(_bp), jnp.asarray(_bt), thresholds=None))
    binned = float(F.binary_average_precision(jnp.asarray(_bp), jnp.asarray(_bt), thresholds=500))
    assert binned == pytest.approx(exact, abs=0.01)


def test_binned_pr_curve_shapes():
    p, r, t = F.binary_precision_recall_curve(jnp.asarray(_bp), jnp.asarray(_bt), thresholds=50)
    assert p.shape == (51,) and r.shape == (51,) and t.shape == (50,)
    p, r, t = F.multiclass_precision_recall_curve(
        jnp.asarray(_mp), jnp.asarray(_mt), num_classes=NUM_CLASSES, thresholds=50
    )
    assert p.shape == (NUM_CLASSES, 51) and r.shape == (NUM_CLASSES, 51) and t.shape == (50,)


def test_binned_stateful_merge_and_ingraph():
    tester = MetricTester()

    def ref(preds, target):
        # binned AP reference: exact sklearn is within binning tolerance at T=500
        return sk.average_precision_score(target, preds)

    m = BinaryAveragePrecision(thresholds=500)
    for i in range(NUM_BATCHES):
        m.update(jnp.asarray(_bin_preds[i]), jnp.asarray(_bin_target[i]))
    assert float(m.compute()) == pytest.approx(ref(_bp, _bt), abs=0.01)

    tester.run_merge_state_test(
        _bin_preds, _bin_target, partial(BinaryAveragePrecision, thresholds=500), ref, atol=0.01
    )
    tester.run_ingraph_sharded_test(
        _bin_preds, _bin_target, partial(BinaryAveragePrecision, thresholds=500), ref, atol=0.01
    )


def test_exact_mode_list_state_stateful():
    m = BinaryPrecisionRecallCurve(thresholds=None)
    for i in range(NUM_BATCHES):
        m.update(jnp.asarray(_bin_preds[i]), jnp.asarray(_bin_target[i]))
    p, r, t = m.compute()
    skp, skr, skt = sk.precision_recall_curve(_bt, _bp)
    np.testing.assert_allclose(np.asarray(p), skp, atol=1e-6)
    np.testing.assert_allclose(np.asarray(r), skr, atol=1e-6)


def test_roc_class_binned():
    m = BinaryROC(thresholds=101)
    m.update(jnp.asarray(_bp), jnp.asarray(_bt))
    fpr, tpr, thr = m.compute()
    assert fpr.shape == (101,) and tpr.shape == (101,)
    # fpr/tpr monotone non-decreasing when thresholds descend
    assert bool(jnp.all(jnp.diff(fpr) >= 0))
    assert bool(jnp.all(jnp.diff(tpr) >= 0))


def test_auroc_ignore_index():
    target = np.where(_bt[:50] == 0, -1, _bt[:50])  # ignore all negatives → degenerate
    # mixed case instead: ignore arbitrary quarter
    target = _bt.copy()
    target[::4] = -1
    ours = float(F.binary_auroc(jnp.asarray(_bp), jnp.asarray(target), thresholds=None, ignore_index=-1))
    keep = target != -1
    ref = sk.roc_auc_score(_bt[keep], _bp[keep])
    assert ours == pytest.approx(ref, abs=1e-6)


def test_multiclass_pr_curve_micro():
    p, r, t = F.multiclass_precision_recall_curve(
        jnp.asarray(_mp), jnp.asarray(_mt), num_classes=NUM_CLASSES, thresholds=None, average="micro"
    )
    t_oh = np.eye(NUM_CLASSES)[_mt].reshape(-1)
    skp, skr, _ = sk.precision_recall_curve(t_oh, _mp.reshape(-1))
    np.testing.assert_allclose(np.asarray(p), skp, atol=1e-6)


def test_multilabel_exact_curve_ignore_index():
    """Regression: exact path must filter ignored samples per label, not count them
    as negatives (found in review; reference remaps only when thresholds given)."""
    preds = jnp.asarray([[0.9, 0.9], [0.8, 0.8], [0.1, 0.1], [0.2, 0.2]])
    target = jnp.asarray([[1, 1], [-1, -1], [0, 0], [-1, -1]])
    p, r, t = F.multilabel_precision_recall_curve(preds, target, num_labels=2, thresholds=None, ignore_index=-1)
    skp, skr, _ = sk.precision_recall_curve([1, 0], [0.9, 0.1])
    np.testing.assert_allclose(np.asarray(p[0]), skp, atol=1e-6)
    np.testing.assert_allclose(np.asarray(r[0]), skr, atol=1e-6)
    ours = float(F.multilabel_auroc(preds, target, num_labels=2, average="macro", thresholds=None, ignore_index=-1))
    assert ours == pytest.approx(1.0)
