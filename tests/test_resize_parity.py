"""Direct parity battery for BOTH extractor resize forks (VERDICT r3 #1).

The reference extractor (``/root/reference/src/torchmetrics/image/fid.py:88-101``)
resizes with torch ``F.interpolate(..., antialias=True)`` or torch-fidelity's
TF1-legacy bilinear. SURVEY §7 names interpolation parity as what makes FID
comparable across implementations, so each fork is anchored here at FID's actual
ratios (arbitrary sizes -> 299, up- and downscale, odd sizes):

- ``antialias=True``  -> directly against torch (installed in the pod), twice:
  (a) the 1-D weight tables recovered from torch by delta-probing (the exact
  semantics check, <=5e-6 per weight), and (b) end-to-end images at the f32
  matmul accumulation envelope.
- ``antialias=False`` -> torch-fidelity is NOT installed here, so the anchor is an
  independent per-pixel gather oracle written from the TF1
  ``half_pixel_centers=False`` definition (``src = i * in/out``, floor/ceil taps
  clamped to the last row) — a gather formulation, deliberately a different
  computation route than the production matmul kernel.

NOTE: torch's own antialias kernel silently returns garbage when any spatial axis
has size 1 (verified: a 64->299 ramp with W=1 comes back all-zeros on torch 2.13
CPU), so every probe here keeps both axes >= 2.
"""

from __future__ import annotations

import numpy as np
import pytest
import torch

from torchmetrics_tpu.functional.image._resize import (
    _antialias_weights_1d,
    resize_bilinear_antialias,
    resize_bilinear_tf1,
)

# FID-realistic ratio grid: odd/even sizes, up- and downscale, identity, non-299
# targets so nothing is special-cased to the flagship shape.
SIZE_GRID = [
    ((64, 64), (299, 299)),      # upscale (CIFAR -> Inception)
    ((75, 113), (299, 299)),     # odd up, anisotropic
    ((171, 171), (299, 299)),    # odd up
    ((256, 256), (299, 299)),    # even up
    ((299, 299), (299, 299)),    # identity
    ((300, 300), (299, 299)),    # near-identity down (worst-case tap layout)
    ((320, 240), (299, 299)),    # mixed up/down per-axis
    ((512, 512), (299, 299)),    # even down
    ((517, 383), (299, 299)),    # odd down, anisotropic
    ((640, 480), (299, 299)),    # VGA down
    ((299, 299), (64, 64)),      # strong down, non-299 target
    ((100, 100), (37, 53)),      # odd small target
    ((50, 50), (150, 150)),      # exact 3x up
]

# Unique 1-D (in, out) axis pairs covered by the grid above.
AXIS_PAIRS = sorted({(i, o) for (ih, iw), (oh, ow) in SIZE_GRID for i, o in ((ih, oh), (iw, ow))})


def _rand_imgs(rng: np.random.Generator, h: int, w: int, n: int = 2, c: int = 3) -> np.ndarray:
    # unit-range content: the normalized extractor input scale
    return rng.uniform(0.0, 1.0, size=(n, c, h, w)).astype(np.float32)


def _torch_aa_weights_1d(in_size: int, out_size: int) -> np.ndarray:
    """Recover torch's antialias resize weight table by resizing per-row deltas
    along H (W held at 8: torch's aa kernel mis-handles size-1 axes)."""
    img = np.zeros((1, 1, in_size, 8), np.float32)
    rows = []
    for j in range(in_size):
        img[:] = 0.0
        img[0, 0, j, :] = 1.0
        out = torch.nn.functional.interpolate(
            torch.from_numpy(img), size=(out_size, 8), mode="bilinear", align_corners=False, antialias=True
        ).numpy()[0, 0, :, 0]
        rows.append(out)
    return np.stack(rows, axis=1)  # (out, in)


@pytest.mark.parametrize(("in_size", "out_size"), AXIS_PAIRS)
def test_antialias_weight_tables_match_torch(in_size, out_size):
    """The exact semantics anchor: our precomputed 1-D triangle-filter tables carry
    the same tap support as torch's and agree to 5e-5 per weight. Torch computes its
    tables in f32 (centers/fractions rounded per-row, measured drift up to ~3e-5 at
    e.g. 300->299); ours are f64-derived then cast, so the residual is torch-side
    rounding, not a semantics difference."""
    ours = _antialias_weights_1d(in_size, out_size)
    ref = _torch_aa_weights_1d(in_size, out_size)
    # identical tap support (structure of the filter — the semantic part)
    np.testing.assert_array_equal(ours > 1e-4, ref > 1e-4)
    np.testing.assert_allclose(ours, ref, atol=5e-5, rtol=0)


@pytest.mark.parametrize(("in_size", "out_size"), SIZE_GRID)
def test_antialias_fork_matches_torch_end_to_end(in_size, out_size):
    """Full images vs torch F.interpolate(antialias=True). Tolerance 1e-4 on
    unit-range data is the f32 envelope: two f32 matmul passes vs torch's f32
    separable conv accumulate in different orders (measured max ~5e-5)."""
    rng = np.random.default_rng(42)
    imgs = _rand_imgs(rng, *in_size)
    ours = np.asarray(resize_bilinear_antialias(imgs, out_size))
    ref = torch.nn.functional.interpolate(
        torch.from_numpy(imgs), size=out_size, mode="bilinear", align_corners=False, antialias=True
    ).numpy()
    np.testing.assert_allclose(ours, ref, atol=1e-4, rtol=0)


def _tf1_gather_oracle(imgs: np.ndarray, out_size) -> np.ndarray:
    """Per-pixel TF1-legacy bilinear (half_pixel_centers=False, align_corners=False):
    src = out_idx * in/out, two taps floor/floor+1 clamped, lerp by the fraction.
    Gather formulation in f64 — independent of the production matmul kernel."""
    out = imgs.astype(np.float64)
    for axis, o in ((-2, out_size[0]), (-1, out_size[1])):
        n = out.shape[axis]
        scale = n / o if o > 1 else 0.0
        src = np.arange(o) * scale
        lo = np.minimum(np.floor(src).astype(np.int64), n - 1)
        hi = np.minimum(lo + 1, n - 1)
        frac = src - lo
        lo_v = np.take(out, lo, axis=axis)
        hi_v = np.take(out, hi, axis=axis)
        shape = [1] * out.ndim
        shape[axis] = o
        f = frac.reshape(shape)
        out = lo_v * (1.0 - f) + hi_v * f
    return out


@pytest.mark.parametrize(("in_size", "out_size"), SIZE_GRID)
def test_tf1_fork_matches_gather_oracle(in_size, out_size):
    rng = np.random.default_rng(7)
    imgs = _rand_imgs(rng, *in_size)
    ours = np.asarray(resize_bilinear_tf1(imgs, out_size))
    ref = _tf1_gather_oracle(imgs, out_size)
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def test_tf1_known_values_integer_upscale():
    """Closed-form TF1 semantics: 2 -> 4 with scale 0.5 gives src = [0, .5, 1, 1.5]
    -> [a, (a+b)/2, b, b] (last tap clamps to the final source row)."""
    a, b = 10.0, 30.0
    img = np.full((1, 1, 2, 2), 0.0, dtype=np.float32)
    img[0, 0, 0, :] = a
    img[0, 0, 1, :] = b
    out = np.asarray(resize_bilinear_tf1(img, (4, 2)))[0, 0, :, 0]
    np.testing.assert_allclose(out, [a, (a + b) / 2, b, b], atol=1e-5)


def test_both_forks_identity_exact():
    rng = np.random.default_rng(3)
    imgs = _rand_imgs(rng, 299, 299, n=1)
    np.testing.assert_allclose(np.asarray(resize_bilinear_antialias(imgs, (299, 299))), imgs, atol=1e-6)
    np.testing.assert_allclose(np.asarray(resize_bilinear_tf1(imgs, (299, 299))), imgs, atol=1e-6)


def test_antialias_upscale_equals_plain_bilinear():
    """On pure upscale the antialias triangle filter support clamps to 1, so the
    fork must coincide with torch's non-antialiased half-pixel bilinear."""
    rng = np.random.default_rng(11)
    imgs = _rand_imgs(rng, 64, 64)
    ours = np.asarray(resize_bilinear_antialias(imgs, (128, 128)))
    ref = torch.nn.functional.interpolate(
        torch.from_numpy(imgs), size=(128, 128), mode="bilinear", align_corners=False, antialias=False
    ).numpy()
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def test_extractor_antialias_false_uses_tf1():
    """Wiring check (round-3 VERDICT weak #1: this branch silently used a third
    semantics): the extractor's antialias=False path must BE the TF1 kernel."""
    import jax.numpy as jnp

    from torchmetrics_tpu.image._extractors import InceptionV3Features, _inception_forward

    rng = np.random.default_rng(5)
    imgs = _rand_imgs(rng, 64, 64)
    for antialias, kernel in ((False, resize_bilinear_tf1), (True, resize_bilinear_antialias)):
        extractor = InceptionV3Features(seed=0, resize_antialias=antialias)
        got = np.asarray(extractor(imgs))
        # float input is scaled to the extractor's 0-255 working range before
        # resize; applying the bare trunk to an independently-resized copy must
        # reproduce the extractor's fused preprocess+trunk exactly
        resized = kernel(jnp.asarray(imgs) * 255.0, (299, 299)).astype(extractor.compute_dtype)
        expected = np.asarray(_inception_forward(extractor.params, resized))
        np.testing.assert_allclose(got, expected, atol=1e-5, rtol=1e-5)
