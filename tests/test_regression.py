"""Regression metrics vs sklearn/scipy/numpy references (SURVEY §2.4, §4)."""

from __future__ import annotations

import numpy as np
import pytest
import scipy.stats
from sklearn.metrics import (
    explained_variance_score,
    mean_absolute_error as sk_mae,
    mean_absolute_percentage_error as sk_mape,
    mean_squared_error as sk_mse,
    mean_squared_log_error as sk_msle,
    mean_tweedie_deviance,
    r2_score as sk_r2,
)

import torchmetrics_tpu as tm
import torchmetrics_tpu.functional as F

from conftest import BATCH_SIZE, NUM_BATCHES, seed_all
from helpers import MetricTester, _assert_allclose

rng = seed_all(7)
PREDS = rng.normal(size=(NUM_BATCHES, BATCH_SIZE)).astype(np.float32)
TARGET = rng.normal(size=(NUM_BATCHES, BATCH_SIZE)).astype(np.float32)
POS_PREDS = np.abs(PREDS) + 0.1
POS_TARGET = np.abs(TARGET) + 0.1
PREDS_2D = rng.normal(size=(NUM_BATCHES, BATCH_SIZE, 3)).astype(np.float32)
TARGET_2D = rng.normal(size=(NUM_BATCHES, BATCH_SIZE, 3)).astype(np.float32)
PROBS_P = rng.uniform(0.1, 1, size=(NUM_BATCHES, BATCH_SIZE, 5)).astype(np.float32)
PROBS_Q = rng.uniform(0.1, 1, size=(NUM_BATCHES, BATCH_SIZE, 5)).astype(np.float32)


class _Case(MetricTester):
    pass


tester = _Case()


def _run_all(preds, target, metric_class, functional, ref, args=None, check_batch=True, ingraph=True, atol=None):
    args = args or {}
    tester.run_functional_metric_test(preds, target, functional, ref, args, atol=atol)
    tester.run_class_metric_test(preds, target, metric_class, ref, args, check_batch=check_batch, atol=atol)
    tester.run_merge_state_test(preds, target, metric_class, ref, args, atol=atol)
    if ingraph:
        tester.run_ingraph_sharded_test(preds, target, metric_class, ref, args, atol=atol)


def test_mean_squared_error():
    _run_all(PREDS, TARGET, tm.MeanSquaredError, F.mean_squared_error, sk_mse)


def test_root_mean_squared_error():
    _run_all(
        PREDS, TARGET, tm.MeanSquaredError, F.mean_squared_error,
        lambda p, t: np.sqrt(sk_mse(t, p)) if False else sk_mse(t, p) ** 0.5,
        args={"squared": False},
    )


def test_mse_ref_order():
    # sklearn signature is (y_true, y_pred); ours is (preds, target) — symmetric for MSE
    assert abs(sk_mse(TARGET[0], PREDS[0]) - sk_mse(PREDS[0], TARGET[0])) < 1e-6


def test_mean_absolute_error():
    _run_all(PREDS, TARGET, tm.MeanAbsoluteError, F.mean_absolute_error, lambda p, t: sk_mae(t, p))


def test_mean_squared_log_error():
    _run_all(POS_PREDS, POS_TARGET, tm.MeanSquaredLogError, F.mean_squared_log_error, lambda p, t: sk_msle(t, p))


def test_mean_absolute_percentage_error():
    _run_all(PREDS, POS_TARGET, tm.MeanAbsolutePercentageError, F.mean_absolute_percentage_error, lambda p, t: sk_mape(t, p))


def _ref_smape(p, t):
    return np.mean(2 * np.abs(p - t) / np.clip(np.abs(t) + np.abs(p), 1.17e-6, None))


def test_symmetric_mape():
    _run_all(PREDS, TARGET, tm.SymmetricMeanAbsolutePercentageError, F.symmetric_mean_absolute_percentage_error, _ref_smape)


def _ref_wmape(p, t):
    return np.sum(np.abs(p - t)) / np.sum(np.abs(t))


def test_weighted_mape():
    _run_all(PREDS, TARGET, tm.WeightedMeanAbsolutePercentageError, F.weighted_mean_absolute_percentage_error, _ref_wmape)


def _ref_logcosh(p, t):
    return np.mean(np.log(np.cosh(np.float64(p) - np.float64(t))))


def test_log_cosh_error():
    _run_all(PREDS, TARGET, tm.LogCoshError, F.log_cosh_error, _ref_logcosh, atol=1e-5)


def test_minkowski_distance():
    p_val = 3.0
    ref = lambda p, t: scipy.spatial.distance.minkowski(p, t, p=p_val)
    import scipy.spatial

    _run_all(PREDS, TARGET, tm.MinkowskiDistance, F.minkowski_distance, ref, args={"p": p_val}, atol=1e-4)


def test_tweedie_deviance():
    for power in (0.0, 1.0, 2.0, 3.0):
        ref = lambda p, t: mean_tweedie_deviance(t, p, power=power)
        _run_all(POS_PREDS, POS_TARGET, tm.TweedieDevianceScore, F.tweedie_deviance_score,
                 ref, args={"power": power}, atol=1e-4)


def test_r2_score():
    _run_all(PREDS, TARGET, tm.R2Score, F.r2_score, lambda p, t: sk_r2(t, p), check_batch=True)


def test_r2_score_multioutput():
    ref = lambda p, t: sk_r2(t, p, multioutput="raw_values")
    tester.run_functional_metric_test(PREDS_2D, TARGET_2D, F.r2_score, ref, {"multioutput": "raw_values"})
    tester.run_class_metric_test(
        PREDS_2D, TARGET_2D, tm.R2Score, ref, metric_args={"num_outputs": 3, "multioutput": "raw_values"}
    )
    tester.run_ingraph_sharded_test(
        PREDS_2D, TARGET_2D, tm.R2Score, ref, metric_args={"num_outputs": 3, "multioutput": "raw_values"}
    )


def _ref_rse(p, t):
    t64, p64 = np.float64(t), np.float64(p)
    return np.sum((t64 - p64) ** 2) / np.sum((t64 - t64.mean()) ** 2)


def test_relative_squared_error():
    tester.run_class_metric_test(PREDS, TARGET, tm.RelativeSquaredError, _ref_rse, check_batch=True)
    tester.run_functional_metric_test(PREDS, TARGET, F.relative_squared_error, _ref_rse)


def test_explained_variance():
    _run_all(PREDS, TARGET, tm.ExplainedVariance, F.explained_variance, lambda p, t: explained_variance_score(t, p))


def test_pearson():
    ref = lambda p, t: scipy.stats.pearsonr(p, t)[0]
    _run_all(PREDS, TARGET, tm.PearsonCorrCoef, F.pearson_corrcoef, ref, atol=1e-5)


def _ref_ccc(p, t):
    p64, t64 = np.float64(p), np.float64(t)
    mx, my = p64.mean(), t64.mean()
    vx, vy = p64.var(ddof=1), t64.var(ddof=1)
    r = scipy.stats.pearsonr(p64, t64)[0]
    return 2 * r * np.sqrt(vx) * np.sqrt(vy) / (vx + vy + (mx - my) ** 2)


def test_concordance():
    _run_all(PREDS, TARGET, tm.ConcordanceCorrCoef, F.concordance_corrcoef, _ref_ccc, atol=1e-5)


def test_spearman():
    ref = lambda p, t: scipy.stats.spearmanr(p, t)[0]
    _run_all(PREDS, TARGET, tm.SpearmanCorrCoef, F.spearman_corrcoef, ref, ingraph=False, atol=1e-5)


def test_kendall():
    ref = lambda p, t: scipy.stats.kendalltau(p, t, variant="b")[0]
    _run_all(PREDS, TARGET, tm.KendallRankCorrCoef, F.kendall_rank_corrcoef, ref, ingraph=False, atol=1e-5)


def test_kendall_with_ties_and_pvalue():
    rng2 = seed_all(3)
    p = rng2.integers(0, 10, size=(1, 64)).astype(np.float32)
    t = rng2.integers(0, 10, size=(1, 64)).astype(np.float32)
    tau, pval = F.kendall_rank_corrcoef(p[0], t[0], t_test=True)
    ref_tau, ref_p = scipy.stats.kendalltau(p[0], t[0], variant="b")
    _assert_allclose(tau, ref_tau, atol=1e-5)
    _assert_allclose(pval, ref_p, atol=1e-4)


def _ref_cosine(p, t):
    num = (p * t).sum(-1)
    den = np.linalg.norm(p, axis=-1) * np.linalg.norm(t, axis=-1)
    return (num / den).sum()


def test_cosine_similarity():
    _run_all(PREDS_2D, TARGET_2D, tm.CosineSimilarity, F.cosine_similarity, _ref_cosine, ingraph=False, atol=1e-4)


def _ref_kl(p, t):
    pn = p / p.sum(-1, keepdims=True)
    qn = t / t.sum(-1, keepdims=True)
    return np.mean([scipy.stats.entropy(pn[i], qn[i]) for i in range(len(pn))])


def test_kl_divergence():
    _run_all(PROBS_P, PROBS_Q, tm.KLDivergence, F.kl_divergence, _ref_kl, atol=1e-5)


def _ref_js(p, t):
    from scipy.spatial.distance import jensenshannon

    pn = p / p.sum(-1, keepdims=True)
    qn = t / t.sum(-1, keepdims=True)
    return np.mean([jensenshannon(pn[i], qn[i], base=np.e) ** 2 for i in range(len(pn))])


def test_js_divergence():
    _run_all(PROBS_P, PROBS_Q, tm.JensenShannonDivergence, F.jensen_shannon_divergence, _ref_js, atol=1e-5)


def _ref_crps(p, t):
    m = p.shape[1]
    diff = np.abs(p - t[:, None]).sum(1) / m
    spread = np.abs(p[:, :, None] - p[:, None, :]).sum((1, 2)) / (2 * m * m)
    return np.mean(diff - spread)


def test_crps():
    preds = rng.normal(size=(NUM_BATCHES, BATCH_SIZE, 8)).astype(np.float32)
    target = rng.normal(size=(NUM_BATCHES, BATCH_SIZE)).astype(np.float32)
    _run_all(preds, target, tm.ContinuousRankedProbabilityScore, F.continuous_ranked_probability_score, _ref_crps, atol=1e-5)


def _ref_csi(p, t, thr=0.5):
    pb, tb = p >= thr, t >= thr
    hits = (pb & tb).sum()
    misses = (~pb & tb).sum()
    fa = (pb & ~tb).sum()
    return hits / (hits + misses + fa)


def test_critical_success_index():
    _run_all(PREDS, TARGET, tm.CriticalSuccessIndex, F.critical_success_index, _ref_csi, args={"threshold": 0.5})


def _ref_nrmse_mean(p, t):
    return np.sqrt(np.mean((np.float64(p) - np.float64(t)) ** 2)) / np.mean(np.float64(t))


def _ref_nrmse_range(p, t):
    return np.sqrt(np.mean((np.float64(p) - np.float64(t)) ** 2)) / (t.max() - t.min())


def _ref_nrmse_std(p, t):
    return np.sqrt(np.mean((np.float64(p) - np.float64(t)) ** 2)) / np.std(np.float64(t))


def _ref_nrmse_l2(p, t):
    return np.sqrt(np.mean((np.float64(p) - np.float64(t)) ** 2)) / np.linalg.norm(np.float64(t))


@pytest.mark.parametrize(
    ("normalization", "ref"),
    [("mean", _ref_nrmse_mean), ("range", _ref_nrmse_range), ("std", _ref_nrmse_std), ("l2", _ref_nrmse_l2)],
)
def test_nrmse(normalization, ref):
    _run_all(
        POS_PREDS, POS_TARGET, tm.NormalizedRootMeanSquaredError, F.normalized_root_mean_squared_error,
        ref, args={"normalization": normalization}, atol=1e-5,
    )


def test_pearson_multioutput():
    def ref(p, t):
        return np.stack([scipy.stats.pearsonr(p[:, i], t[:, i])[0] for i in range(p.shape[1])])

    tester.run_class_metric_test(
        PREDS_2D, TARGET_2D, tm.PearsonCorrCoef, ref, metric_args={"num_outputs": 3}, atol=1e-5
    )


def test_spearman_multioutput():
    def ref(p, t):
        return np.stack([scipy.stats.spearmanr(p[:, i], t[:, i])[0] for i in range(p.shape[1])])

    tester.run_class_metric_test(
        PREDS_2D, TARGET_2D, tm.SpearmanCorrCoef, ref, metric_args={"num_outputs": 3}, atol=1e-5
    )


def test_invalid_args():
    with pytest.raises(ValueError):
        tm.MeanSquaredError(squared="yes")
    with pytest.raises(Exception):
        tm.MinkowskiDistance(p=0.5)
    with pytest.raises(ValueError):
        tm.KLDivergence(reduction="bad")
    with pytest.raises(ValueError):
        tm.NormalizedRootMeanSquaredError(normalization="bad")
    with pytest.raises(ValueError):
        tm.R2Score(multioutput="bad")
    with pytest.raises(ValueError):
        tm.KendallRankCorrCoef(variant="z")
