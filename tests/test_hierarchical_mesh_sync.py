"""Hierarchical multi-slice sync: metric-state reduction over a 2-D (dcn, ici) mesh.

SURVEY §2.12 names the TPU-native multi-slice design: psum-family reductions ride
ICI within a slice and DCN across slices. These tests run the 8-device CPU mesh
as 2 slices x 4 chips and verify:

- single-shot reduction over BOTH axes equals the global value,
- the hierarchical two-stage formulation (reduce over "ici", then over "dcn")
  equals the single-shot reduction for every reduction kind,
- the fused MetricCollection reduces correctly over the 2-D mesh.
"""

from __future__ import annotations

import jax
from torchmetrics_tpu.parallel import shard_map as _shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import torchmetrics_tpu as tm
from tests.helpers import _assert_allclose
from torchmetrics_tpu.parallel.sync import reduce_over_axis

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device CPU mesh")


def _mesh():
    return jax.make_mesh((2, 4), ("dcn", "ici"))


@pytest.mark.parametrize("fx", ["sum", "mean", "max", "min", "cat"])
def test_two_stage_equals_single_shot(fx):
    mesh = _mesh()
    rng = np.random.default_rng(0)
    per_device = rng.random((8, 4), dtype=np.float32)
    data = jax.device_put(
        per_device.reshape(2, 4, 4), NamedSharding(mesh, P("dcn", "ici", None))
    )

    def one_shot(x):
        return reduce_over_axis(x.reshape(4), fx, ("dcn", "ici"))

    def hierarchical(x):
        local = reduce_over_axis(x.reshape(4), fx, "ici")  # intra-slice (ICI)
        return reduce_over_axis(local, fx, "dcn")  # cross-slice (DCN)

    run = lambda fn: np.asarray(
        jax.jit(
            _shard_map(
                fn, mesh=mesh, in_specs=(P("dcn", "ici", None),), out_specs=P(), check_vma=False
            )
        )(data)
    )
    single = run(one_shot)
    if fx == "cat":
        # gather order differs between the fused and staged formulations; the
        # multiset of rows is the contract (reference sync also documents
        # order-insensitivity of gathered cat states)
        np.testing.assert_allclose(
            np.sort(single.reshape(-1, 4), axis=0), np.sort(run(hierarchical).reshape(-1, 4), axis=0)
        )
        np.testing.assert_allclose(np.sort(single.reshape(-1, 4), axis=0), np.sort(per_device, axis=0))
        return
    staged = run(hierarchical)
    np.testing.assert_allclose(single, staged, rtol=1e-6)
    expected = {
        "sum": per_device.sum(0),
        "mean": per_device.mean(0),
        "max": per_device.max(0),
        "min": per_device.min(0),
    }[fx]
    np.testing.assert_allclose(single, expected, rtol=1e-6)


def test_metric_state_reduction_over_2d_mesh():
    """A real metric's reduce_state over both axes == single-device total."""
    mesh = _mesh()
    rng = np.random.default_rng(1)
    preds = rng.normal(size=(64, 5)).astype(np.float32)
    target = rng.integers(0, 5, 64).astype(np.int32)

    metric = tm.MulticlassAccuracy(5, average="micro", validate_args=False)

    def shard_fn(p, t):
        state = metric.update_state(metric.init_state(), p, t)
        state = metric.reduce_state(state, ("dcn", "ici"))
        return state

    fn = jax.jit(
        _shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(("dcn", "ici")), P(("dcn", "ici"))),
            out_specs=P(), check_vma=False,
        )
    )
    synced = fn(jnp.asarray(preds), jnp.asarray(target))
    value = metric.compute_state(synced)

    single = tm.MulticlassAccuracy(5, average="micro", validate_args=False)
    single.update(jnp.asarray(preds), jnp.asarray(target))
    _assert_allclose(value, single.compute())


def test_fused_collection_over_2d_mesh():
    mesh = _mesh()
    rng = np.random.default_rng(2)
    probs = jax.nn.softmax(jnp.asarray(rng.normal(size=(64, 10)).astype(np.float32)))
    target = jnp.asarray(rng.integers(0, 10, 64).astype(np.int32))

    collection = tm.MetricCollection({
        "acc": tm.classification.MulticlassAccuracy(10, average="micro", validate_args=False),
        "confmat": tm.classification.MulticlassConfusionMatrix(10, validate_args=False),
    })
    pure = collection.as_pure()

    def shard_fn(p, t):
        states = pure.update(pure.init(), p, t)
        return pure.reduce(states, ("dcn", "ici"))

    fn = jax.jit(
        _shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(("dcn", "ici")), P(("dcn", "ici"))),
            out_specs=P(), check_vma=False,
        )
    )
    values = jax.jit(pure.compute)(fn(probs, target))

    ref = tm.MetricCollection({
        "acc": tm.classification.MulticlassAccuracy(10, average="micro", validate_args=False),
        "confmat": tm.classification.MulticlassConfusionMatrix(10, validate_args=False),
    })
    ref.update(probs, target)
    _assert_allclose(values, ref.compute())
