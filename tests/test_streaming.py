"""Streaming plane (ISSUE 10): windowed/decayed metrics + async double-buffered sync.

Contracts pinned here:

- **Window-parity oracle**: ``SlidingWindow(metric, N)`` over a stream equals
  a fresh plain metric fed only the trailing ``N`` batches — fuzzed across
  metric families (classification, aggregation, regression, confusion-matrix,
  list/cat states, custom-merge) and dtypes including bf16.
- **Decay closed form**: ``ExponentialDecay`` sum leaves equal
  ``Σ d^k x_{n-k}`` exactly; mean-style ratios are the d-weighted average.
- **Async-vs-blocking parity**: ``MetricCollection.sync(async_=True)`` commits
  states BITWISE equal to the blocking coalesced sync, while the collection
  keeps updating during the overlap; a ``FlakyGather`` failing mid-overlap
  rolls back (commit installs nothing), and a retry policy recovers it.
- **Version-skew mailbox skip**: a metadata row from another coalesce layout
  version falls back to the per-leaf plane in lockstep and deposits NO fleet
  mailbox rows — rollups degrade to local instead of misdecoding.

Worlds are simulated through the ``dist_sync_fn`` seam with deterministic
replay fakes (same pattern as ``tests/test_coalesced_sync.py``).
"""

import importlib.util
import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchmetrics_tpu as tm
from torchmetrics_tpu import Metric, MetricCollection
from torchmetrics_tpu import observability as obs
from torchmetrics_tpu.aggregation import CatMetric, MaxMetric, MeanMetric, MinMetric, SumMetric
from torchmetrics_tpu.classification import (
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassPrecision,
)
from torchmetrics_tpu.metric import DECAY_WEIGHT_KEY, WINDOW_COUNT_KEY, WINDOW_CURSOR_KEY
from torchmetrics_tpu.parallel import AsyncSyncHandle
from torchmetrics_tpu.parallel import coalesce as C
from torchmetrics_tpu.parallel import sync as S
from torchmetrics_tpu.regression import MeanSquaredError
from torchmetrics_tpu.reliability import FlakyGather, ReliabilityConfig, RetryPolicy
from torchmetrics_tpu.serving import ServingConfig, ServingEngine
from torchmetrics_tpu.streaming import DriftMonitor, ExponentialDecay, SlidingWindow
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError, TransientRuntimeError

pytestmark = pytest.mark.streaming


# --------------------------------------------------------------------- helpers


class LastValueMetric(Metric):
    """Custom-merge metric (merge keeps the INCOMING side) — pins that the
    window fold runs the metric's own merge sequentially in stream order."""

    def __init__(self):
        super().__init__()
        self.add_state("v", default=np.zeros(()), dist_reduce_fx=None)
        self.add_state("seen", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, x):
        return {"v": jnp.asarray(x, jnp.float32), "seen": jnp.ones((), jnp.float32)}

    def _merge(self, a, b):
        return {"v": b.get("v", a["v"]), "seen": a["seen"] + b.get("seen", 0.0)}

    def _compute(self, state):
        return state["v"]


def _cls_batches(rng, n, num_classes=5, batch=16, dtype=np.float32):
    out = []
    for _ in range(n):
        p = jnp.asarray(rng.normal(size=(batch, num_classes)).astype(dtype))
        t = jnp.asarray(rng.integers(0, num_classes, batch, dtype=np.int32))
        out.append((p, t))
    return out


def _value_close(a, b, rtol=1e-5, atol=1e-6):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(
            np.asarray(x, np.float64), np.asarray(y, np.float64), rtol=rtol, atol=atol
        )


class SimWorld:
    """Replay ``dist_sync_fn``: N simulated ranks answering the coalesced
    plane's collectives deterministically. Retry-safe: a metadata vector
    restarts the bucket sequence, so a retried sync replays from the top."""

    def __init__(self, ranks):
        self.ranks = ranks  # [(states_list, reductions_list), ...]
        self.metas = None
        self.bucket_i = 0
        self.calls = 0

    def __call__(self, value, group=None):
        self.calls += 1
        v = np.asarray(value)
        if v.dtype.kind == "i" and v.ndim == 1 and v.size >= 4 and int(v[0]) == 0x436F414C:
            self.metas = [C.build_local_metadata(s, r) for s, r in self.ranks]
            self.bucket_i = 0
            return [jnp.asarray(m) for m in self.metas]
        k = self.bucket_i
        self.bucket_i += 1
        return [C.build_bucket_payload(s, r, k, self.metas) for s, r in self.ranks]


def _freeze_states(coll):
    return (
        [{k: (list(v) if isinstance(v, list) else v) for k, v in m._state.items()} for m in coll.values()],
        [m._reductions for m in coll.values()],
    )


# ------------------------------------------------------------ window parity


def _oracle_check(sw, factory, batches, rtol=1e-5, atol=1e-6):
    """THE window-parity oracle, tier-aware: the wrapped value equals a fresh
    metric fed exactly the trailing ``covered_updates()`` batches. For the
    ring tier covered == min(n, window) (per-update exact); the dual/two-stack
    tiers advance the boundary in hops, and covered names the exact span."""
    for b in batches:
        sw.update(*b)
    cov = sw.covered_updates()
    assert cov >= min(len(batches), sw.window)  # never LESS context than asked
    plain = factory()
    for b in batches[-cov:] if cov else []:
        plain.update(*b)
    _value_close(sw.compute(), plain.compute(), rtol=rtol, atol=atol)


WINDOW_FAMILIES = [
    ("accuracy", lambda: MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)),
    ("precision", lambda: MulticlassPrecision(num_classes=5, average="macro", validate_args=False)),
    ("confmat", lambda: MulticlassConfusionMatrix(num_classes=5, validate_args=False)),
]


@pytest.mark.parametrize("name,factory", WINDOW_FAMILIES, ids=[f[0] for f in WINDOW_FAMILIES])
@pytest.mark.parametrize("tier", ["auto", "dual", "two_stack", "ring"])
@pytest.mark.parametrize("window,stream", [(4, 11), (5, 5), (8, 3)])
def test_window_parity_classification(name, factory, tier, window, stream):
    """The oracle across every tier (forced explicitly — the ISSUE 12
    acceptance bar), for windows smaller, equal, and larger than the stream.
    These sum-reduced classification metrics auto-select the dual tier."""
    rng = np.random.default_rng(hash((name, window, stream)) % (2**32))
    batches = _cls_batches(rng, stream)
    sw = SlidingWindow(factory(), window, tier=tier)
    if tier == "auto":
        assert sw.tier == "dual"  # sum-reduced states collapse to the pair
    _oracle_check(sw, factory, batches)


def test_window_ring_tier_exact_trailing_n():
    """The forced ring stays per-update exact: covered == min(n, window) at
    EVERY phase (the PR 10 contract, now an opt-in tier)."""
    rng = np.random.default_rng(11)
    batches = _cls_batches(rng, 11)
    mk = WINDOW_FAMILIES[0][1]
    sw = SlidingWindow(mk(), 4, tier="ring")
    for i, (p, t) in enumerate(batches):
        sw.update(p, t)
        assert sw.covered_updates() == min(i + 1, 4)
    plain = mk()
    for p, t in batches[-4:]:
        plain.update(p, t)
    _value_close(sw.compute(), plain.compute())


@pytest.mark.parametrize("factory,feed,expect_tier", [
    (SumMetric, "scalar", "dual"),
    (MeanMetric, "vector", "dual"),
    (MaxMetric, "scalar", "two_stack"),
    (MinMetric, "vector", "two_stack"),
    (MeanSquaredError, "pair", "dual"),
])
@pytest.mark.parametrize("tier", ["auto", "ring"])
def test_window_parity_aggregation_regression(factory, feed, expect_tier, tier):
    rng = np.random.default_rng(3)
    window, stream = 3, 9
    sw = SlidingWindow(factory(), window, tier=tier)
    if tier == "auto":
        assert sw.tier == expect_tier
    batches = []
    for _ in range(stream):
        if feed == "scalar":
            batches.append((float(rng.normal()),))
        elif feed == "vector":
            batches.append((jnp.asarray(rng.normal(size=(4,)).astype(np.float32)),))
        else:
            batches.append((
                jnp.asarray(rng.normal(size=(6,)).astype(np.float32)),
                jnp.asarray(rng.normal(size=(6,)).astype(np.float32)),
            ))
    _oracle_check(sw, factory, batches)


@pytest.mark.parametrize("tier,pane", [("dual", None), ("two_stack", None),
                                       ("two_stack", 3), ("ring", None)])
def test_window_parity_tier_fuzz(tier, pane):
    """Per-tier fuzz at awkward window/stream phases, incl. a pane that does
    not divide the window (two-stack rounds the effective window UP)."""
    rng = np.random.default_rng(29)
    mk = lambda: MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)
    for window, stream in [(4, 11), (7, 23), (16, 5), (10, 37)]:
        batches = _cls_batches(rng, stream)
        sw = SlidingWindow(mk(), window, tier=tier, pane=pane)
        _oracle_check(sw, mk, batches)


@pytest.mark.parametrize("tier", ["dual", "two_stack", "ring"])
def test_window_parity_bf16_inputs(tier):
    rng = np.random.default_rng(7)
    window = 3
    batches = _cls_batches(rng, 7, dtype=np.float32)
    batches = [(p.astype(jnp.bfloat16), t) for p, t in batches]
    mk = lambda: MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)
    sw = SlidingWindow(mk(), window, tier=tier)
    _oracle_check(sw, mk, batches, rtol=2e-2, atol=1e-2)


def test_window_parity_list_state_bounded():
    """CatMetric: list ('cat') contributions live in a bounded host ring —
    value parity with the trailing window AND no growth past the window."""
    window = 4
    sw = SlidingWindow(CatMetric(), window)
    vals = [jnp.asarray(np.full((3,), float(i), np.float32)) for i in range(9)]
    for v in vals:
        sw.update(v)
    plain = CatMetric()
    for v in vals[-window:]:
        plain.update(v)
    _value_close(sw.compute(), plain.compute())
    live = [b for b in sw._append_ring if b is not None]
    assert len(live) == window  # the host ring never outgrows the window
    assert sum(len(b.get("value", [])) for b in live) == window


def test_window_custom_merge_stream_order():
    """Custom-merge metrics fold sequentially through their OWN merge in
    stream order — LastValueMetric's window value is the newest batch."""
    sw = SlidingWindow(LastValueMetric(), 3)
    for x in [1.0, 2.0, 3.0, 4.0]:
        sw.update(x)
    assert float(sw.compute()) == 4.0
    assert float(np.asarray(sw.window_state()["seen"])) == 3.0


def test_window_forward_batch_value_and_reset():
    sw = SlidingWindow(SumMetric(), 2)
    assert float(sw.forward(5.0)) == 5.0  # batch-only value
    sw.update(7.0)
    assert float(sw.compute()) == 12.0
    sw.reset()
    assert sw._ring is None and sw.update_count == 0
    sw.update(1.0)
    assert float(sw.compute()) == 1.0


def test_window_one_compile_and_telemetry():
    """One fresh compile serves every windowed update (now under the dual
    tier's ``wdual`` tag); window_rolls ticks per update, window_rotations
    per dual block rotation, and the window_roll event fires per wrap."""
    rng = np.random.default_rng(5)
    batches = _cls_batches(rng, 10)
    with obs.telemetry_session() as rec:
        sw = SlidingWindow(MulticlassAccuracy(num_classes=5, average="micro", validate_args=False), 4)
        assert sw.tier == "dual"
        for p, t in batches:
            sw.update(p, t)
    snap = rec.counters.snapshot()
    wkeys = {k: v for k, v in snap.per_key.items() if k.endswith(".wdual")}
    assert sum(v["compiles"] for v in wkeys.values()) == 1
    assert sum(v["compiles"] + v["cache_hits"] + v["aot_hits"] for v in wkeys.values()) == 10
    assert snap["window_rolls"] == 10
    assert snap["window_rotations"] == 2  # dual blocks rotated at updates 4 and 8
    wraps = rec.events_of("window_roll")
    assert len(wraps) == 2  # 10 updates / window 4 → wraps at 4 and 8
    assert wraps[0].payload["window"] == 4
    assert wraps[0].payload["tier"] == "dual" and wraps[0].tag == "wdual"


def test_window_ring_one_compile_unchanged():
    """The forced ring keeps its PR 10 contract: one wupdate compile, a roll
    per update, zero rotations (rotation is a dual/two-stack notion)."""
    rng = np.random.default_rng(6)
    batches = _cls_batches(rng, 6)
    with obs.telemetry_session() as rec:
        sw = SlidingWindow(
            MulticlassAccuracy(num_classes=5, average="micro", validate_args=False), 3,
            tier="ring",
        )
        for p, t in batches:
            sw.update(p, t)
    snap = rec.counters.snapshot()
    wkeys = {k: v for k, v in snap.per_key.items() if k.endswith(".wupdate")}
    assert sum(v["compiles"] for v in wkeys.values()) == 1
    assert snap["window_rolls"] == 6
    assert snap["window_rotations"] == 0


def test_window_rejects_host_and_composition():
    with pytest.raises(TorchMetricsUserError):
        SlidingWindow(SumMetric() + SumMetric(), 4)  # CompositionalMetric: no pure core
    with pytest.raises(ValueError):
        SlidingWindow(SumMetric(), 0)
    with pytest.raises(TorchMetricsUserError):
        sw = SlidingWindow(SumMetric(), 2)
        sw.merge_state({"sum_value": 1.0})


# ------------------------------------------------------------------- decay


def test_decay_sum_closed_form():
    d = 0.75
    xs = [1.0, -2.0, 3.0, 0.5, 4.0]
    ed = ExponentialDecay(SumMetric(), decay=d)
    for x in xs:
        ed.update(x)
    n = len(xs)
    expect = sum((d ** (n - 1 - i)) * x for i, x in enumerate(xs))
    np.testing.assert_allclose(float(ed.compute()), expect, rtol=1e-6)
    np.testing.assert_allclose(
        float(np.asarray(ed.decayed_count)), sum(d**k for k in range(n)), rtol=1e-6
    )


def test_decay_mean_weighted_average():
    """MeanMetric keeps sum+weight states, so the decayed value is exactly
    the exponentially weighted average of the batch means."""
    d = 0.5
    xs = [2.0, 4.0, 8.0]
    ed = ExponentialDecay(MeanMetric(), decay=d)
    for x in xs:
        ed.update(x)
    n = len(xs)
    num = sum((d ** (n - 1 - i)) * x for i, x in enumerate(xs))
    den = sum(d**k for k in range(n))
    np.testing.assert_allclose(float(ed.compute()), num / den, rtol=1e-6)


def test_decay_halflife_semantics():
    ed = ExponentialDecay(SumMetric(), halflife=2.0)
    assert ed.decay == pytest.approx(2.0 ** (-1.0 / 2.0))
    # a batch `halflife` updates old carries half the current weight
    ed.update(1.0)
    ed.update(0.0)
    ed.update(0.0)
    np.testing.assert_allclose(float(ed.compute()), 0.5, rtol=1e-6)


def test_decay_accuracy_constant_stream():
    rng = np.random.default_rng(9)
    p, t = _cls_batches(rng, 1)[0]
    plain = MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)
    plain.update(p, t)
    ed = ExponentialDecay(
        MulticlassAccuracy(num_classes=5, average="micro", validate_args=False), halflife=8
    )
    for _ in range(6):
        ed.update(p, t)
    _value_close(ed.compute(), plain.compute())


def test_decay_one_compile_and_rejections():
    with obs.telemetry_session() as rec:
        ed = ExponentialDecay(SumMetric(), decay=0.9)
        for x in range(8):
            ed.update(float(x))
    snap = rec.counters.snapshot()
    dkeys = {k: v for k, v in snap.per_key.items() if k.endswith(".dupdate")}
    assert sum(v["compiles"] for v in dkeys.values()) == 1
    with pytest.raises(TorchMetricsUserError):
        ExponentialDecay(CatMetric(), decay=0.9)  # concat states cannot decay
    with pytest.raises(TorchMetricsUserError):
        ExponentialDecay(LastValueMetric(), decay=0.9)  # custom merge
    with pytest.raises(ValueError):
        ExponentialDecay(SumMetric(), decay=1.5)
    with pytest.raises(ValueError):
        ExponentialDecay(SumMetric())  # neither halflife nor decay


# ------------------------------------------------- async double-buffered sync


def _mk_coll():
    return MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=5, average="micro", validate_args=False),
            "s": SumMetric(),
            "cat": CatMetric(),
        },
        compute_groups=False,
    )


def _feed(coll, rng, n=2):
    for p, t in _cls_batches(rng, n):
        coll["acc"].update(p, t)
    coll["s"].update(3.0)
    coll["cat"].update(jnp.asarray(rng.normal(size=(2,)).astype(np.float32)))


def _remote_coll():
    rng = np.random.default_rng(99)
    coll = _mk_coll()
    _feed(coll, rng, n=3)
    coll["s"].update(11.0)
    return coll


def test_async_sync_bitwise_parity_with_overlap():
    rng_a, rng_b = np.random.default_rng(1), np.random.default_rng(1)
    coll_a, coll_b = _mk_coll(), _mk_coll()
    _feed(coll_a, rng_a)
    _feed(coll_b, rng_b)
    remote = _remote_coll()
    force = lambda: True
    coll_a.sync(
        distributed_available=force,
        dist_sync_fn=SimWorld([_freeze_states(coll_a), _freeze_states(remote)]),
    )
    handle = coll_b.sync(
        async_=True, distributed_available=force,
        dist_sync_fn=SimWorld([_freeze_states(coll_b), _freeze_states(remote)]),
    )
    # the current window keeps updating during the overlap
    coll_b["s"].update(100.0)
    coll_b["cat"].update(jnp.asarray([42.0], jnp.float32))
    handle.commit()
    assert handle.committed and handle.gather_s >= 0.0
    for key in coll_a.keys(keep_base=True):
        sa, sb = coll_a[key]._state, coll_b[key]._state
        assert set(sa) == set(sb)
        for name in sa:
            va, vb = sa[name], sb[name]
            if isinstance(va, list):
                assert len(va) == len(vb)
                for x, y in zip(va, vb):
                    np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
            else:
                assert jnp.asarray(va).dtype == jnp.asarray(vb).dtype
                np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    # unsync restores the overlap-updated CURRENT window, nothing lost:
    # local 3.0 + remote (3.0 + 11.0) synced; live = local 3.0 + overlap 100.0
    synced_sum = float(np.asarray(coll_b["s"]._state["sum_value"]))
    assert synced_sum == pytest.approx(17.0)
    coll_b.unsync()
    live_sum = float(np.asarray(coll_b["s"]._state["sum_value"]))
    assert live_sum == pytest.approx(103.0)
    assert not coll_b["s"]._is_synced


def test_async_sync_flaky_gather_rollback_mid_overlap():
    """A transient gather failure mid-overlap commits NOTHING: every member
    keeps its last good (live) state and the error surfaces at commit()."""
    rng = np.random.default_rng(2)
    coll = _mk_coll()
    _feed(coll, rng)
    world = SimWorld([_freeze_states(coll), _freeze_states(_remote_coll())])
    flaky = FlakyGather(inner=world, fail_times=10)  # never recovers
    before = {
        key: {k: (list(v) if isinstance(v, list) else np.asarray(v)) for k, v in coll[key]._state.items()}
        for key in coll.keys(keep_base=True)
    }
    handle = coll.sync(async_=True, distributed_available=lambda: True, dist_sync_fn=flaky)
    coll["s"].update(50.0)  # overlap update — must survive the rollback
    with pytest.raises(TransientRuntimeError):
        handle.commit()
    for key in coll.keys(keep_base=True):
        assert not coll[key]._is_synced
    np.testing.assert_array_equal(
        np.asarray(coll["s"]._state["sum_value"]),
        np.asarray(before["s"]["sum_value"]) + 50.0,
    )


def test_async_sync_flaky_gather_retry_recovers():
    rng_a, rng_b = np.random.default_rng(4), np.random.default_rng(4)
    rel = ReliabilityConfig(retry=RetryPolicy(max_attempts=3, backoff_base=0.001))

    def mk(reliability):
        coll = MetricCollection(
            {"acc": MulticlassAccuracy(num_classes=5, average="micro", validate_args=False,
                                       reliability=reliability),
             "s": SumMetric()},
            compute_groups=False,
        )
        return coll

    coll_a, coll_b = mk(None), mk(rel)
    for coll, rng in ((coll_a, rng_a), (coll_b, rng_b)):
        for p, t in _cls_batches(rng, 2):
            coll["acc"].update(p, t)
        coll["s"].update(3.0)
    remote = mk(None)
    for p, t in _cls_batches(np.random.default_rng(77), 2):
        remote["acc"].update(p, t)
    remote["s"].update(5.0)
    coll_a.sync(
        distributed_available=lambda: True,
        dist_sync_fn=SimWorld([_freeze_states(coll_a), _freeze_states(remote)]),
    )
    flaky = FlakyGather(
        inner=SimWorld([_freeze_states(coll_b), _freeze_states(remote)]), fail_times=1
    )
    handle = coll_b.sync(async_=True, distributed_available=lambda: True, dist_sync_fn=flaky)
    handle.commit()
    assert flaky.failures == 1
    for key in coll_a.keys(keep_base=True):
        for name in coll_a[key]._state:
            np.testing.assert_array_equal(
                np.asarray(coll_a[key]._state[name]), np.asarray(coll_b[key]._state[name])
            )


def test_async_sync_noop_and_contracts():
    coll = _mk_coll()
    _feed(coll, np.random.default_rng(6))
    handle = coll.sync(async_=True)  # distributed unavailable → noop handle
    assert handle.done
    assert handle.commit() == []
    for key in coll.keys(keep_base=True):
        assert not coll[key]._is_synced
    with pytest.raises(TorchMetricsUserError):
        handle.commit()  # one-shot
    # mixed gather seams cannot async
    coll2 = _mk_coll()
    coll2["s"].dist_sync_fn = lambda v, g: [v]
    with pytest.raises(TorchMetricsUserError):
        coll2.sync(async_=True, distributed_available=lambda: True)


def test_async_sync_telemetry_overlap_accounting():
    rng = np.random.default_rng(8)
    coll = _mk_coll()
    _feed(coll, rng)
    with obs.telemetry_session() as rec:
        handle = coll.sync(
            async_=True, distributed_available=lambda: True,
            dist_sync_fn=SimWorld([_freeze_states(coll), _freeze_states(_remote_coll())]),
        )
        handle.commit()
        coll.unsync()
    snap = rec.counters.snapshot()
    assert snap["async_syncs"] == 1
    assert snap["sync_calls"] == 1
    events = rec.events_of("async_sync")
    assert len(events) == 1
    payload = events[0].payload
    assert 0.0 <= payload["overlap_pct"] <= 100.0
    assert payload["collectives"] >= 1 and not payload["fallback"]


# ----------------------------------------------------- serving engine satellites


def _serve_batch(rng, num_classes=4, batch=8):
    return (
        rng.normal(size=(batch, num_classes)).astype(np.float32),
        rng.integers(0, num_classes, batch, dtype=np.int32),
    )


@pytest.mark.serving
def test_vmapped_compute_all_parity_one_compile():
    rng = np.random.default_rng(12)
    preds, target = _serve_batch(rng)
    mk = lambda: MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
    with obs.telemetry_session() as rec:
        eng = ServingEngine(mk(), ServingConfig(capacity=64, megabatch_size=16))
        for t in range(40):
            eng.update(t, preds, target)
            if t % 3 == 0:  # vary per-tenant history
                eng.update(t, preds, target)
        eng.flush()
        vals = eng.compute_all()
        assert set(vals) == set(range(40))
        for t in (0, 7, 39):
            np.testing.assert_allclose(
                np.asarray(vals[t]), np.asarray(eng.compute(t)), rtol=1e-6
            )
        vals2 = eng.compute_all()
        np.testing.assert_array_equal(np.asarray(vals2[5]), np.asarray(vals[5]))
    snap = rec.counters.snapshot()
    vkeys = {k: v for k, v in snap.per_key.items() if k.endswith(".vcompute")}
    assert sum(v["compiles"] for v in vkeys.values()) == 1  # one compile, whole fleet
    assert sum(v["cache_hits"] for v in vkeys.values()) == 1  # second compute_all reuses it


@pytest.mark.serving
def test_vmapped_compute_all_spilled_fallback():
    rng = np.random.default_rng(13)
    preds, target = _serve_batch(rng)
    mk = lambda: MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
    eng = ServingEngine(mk(), ServingConfig(capacity=8, megabatch_size=4))
    for t in range(16):  # half the fleet spills
        eng.update(t, preds, target)
    eng.flush()
    vals = eng.compute_all()
    assert set(vals) == set(range(16))
    for t in range(16):
        np.testing.assert_allclose(np.asarray(vals[t]), np.asarray(eng.compute(t)), rtol=1e-6)


@pytest.mark.serving
def test_admission_rate_limit_sheds():
    rng = np.random.default_rng(14)
    preds, target = _serve_batch(rng)
    mk = lambda: MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
    with obs.telemetry_session() as rec:
        eng = ServingEngine(
            mk(), ServingConfig(capacity=8, megabatch_size=4, max_tenants_per_sec=5)
        )
        clock = {"t": 1000.0}
        eng._clock = lambda: clock["t"]
        results = [eng.update(i % 4, preds, target) for i in range(8)]
        assert results == [True] * 5 + [False] * 3  # burst = one second of tokens
        assert eng.stats["rejected_batches"] == 3
        clock["t"] += 0.5  # 0.5s * 5/s = 2.5 tokens back
        assert eng.update(0, preds, target) is True
        assert eng.update(1, preds, target) is True
        assert eng.update(2, preds, target) is False
    snap = rec.counters.snapshot()
    assert snap["serve_rejected"] == 4
    rejected = rec.events_of("serve_rejected")
    assert len(rejected) == 4 and rejected[0].tag == "admission"
    assert "rejected_batches" in eng.summary()
    with pytest.raises(ValueError):
        ServingConfig(max_tenants_per_sec=0)


@pytest.mark.serving
def test_engine_sync_async_global_snapshot():
    """World-of-one engine sync: the committed global stacks equal the frozen
    local stacks, the live stacks keep serving (reset_window rotates them)."""
    rng = np.random.default_rng(15)
    preds, target = _serve_batch(rng)
    mk = lambda: MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
    eng = ServingEngine(mk(), ServingConfig(capacity=8, megabatch_size=4))
    for t in range(6):
        eng.update(t, preds, target)
    eng.flush()
    frozen_ref = {
        key: {k: np.asarray(v) for k, v in cls.stacked.items()}
        for key, cls in eng._classes.items()
    }
    handle = eng.sync_async()
    eng.update(0, preds, target)  # live stack keeps serving during the overlap
    eng.flush()
    synced = handle.commit()
    assert set(synced) == set(frozen_ref)
    for key, stack in synced.items():
        for name, v in stack.items():
            np.testing.assert_array_equal(np.asarray(v), frozen_ref[key][name])
    # reset_window rotates: fresh default stacks, frozen buffers ride the handle
    handle2 = eng.sync_async(reset_window=True)
    for cls in eng._classes.values():
        counts = np.asarray(cls.stacked["__tenant_n"])
        np.testing.assert_array_equal(counts, np.zeros_like(counts))
    handle2.commit()


class _MeanTagMetric(Metric):
    """A bare 'mean'-reduced state: rowwise cross-rank folding cannot weight it."""

    def __init__(self):
        super().__init__()
        self.add_state("m", default=np.zeros(()), dist_reduce_fx="mean")

    def _batch_state(self, x):
        return {"m": jnp.asarray(x, jnp.float32).mean()}

    def _compute(self, state):
        return state["m"]


@pytest.mark.serving
def test_admission_sub_unit_rate_still_admits():
    """A rate below 1/s must behave as a slow limit, not a permanent outage:
    the bucket floors at one whole token."""
    rng = np.random.default_rng(21)
    preds, target = _serve_batch(rng)
    mk = lambda: MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
    eng = ServingEngine(mk(), ServingConfig(capacity=4, megabatch_size=2, max_tenants_per_sec=0.5))
    clock = {"t": 0.0}
    eng._clock = lambda: clock["t"]
    assert eng.update(0, preds, target) is True  # boot burst: one whole token
    assert eng.update(1, preds, target) is False
    clock["t"] += 2.5  # 2.5s * 0.5/s = 1.25 tokens
    assert eng.update(1, preds, target) is True
    assert eng.update(2, preds, target) is False


@pytest.mark.serving
def test_engine_sync_async_flushes_pending_and_rotates_spilled():
    rng = np.random.default_rng(22)
    preds, target = _serve_batch(rng)
    mk = lambda: MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
    # pending-queue flush: an admitted-but-undispatched batch lands in the
    # window it arrived in
    eng = ServingEngine(mk(), ServingConfig(capacity=8, megabatch_size=4, auto_flush=False))
    eng.update(0, preds, target)
    assert eng._tenants[0].pending == 1
    handle = eng.sync_async()
    assert eng._tenants[0].pending == 0  # flushed before the freeze
    synced = handle.commit()
    (stack,) = synced.values()
    # real rows only — the reserved scratch row (index `capacity`) absorbs
    # megabatch padding and legitimately accumulates a count of its own
    assert float(np.asarray(stack["__tenant_n"])[:8].sum()) == pytest.approx(1.0)
    # spilled tenants rotate with the fleet under reset_window
    churn = ServingEngine(mk(), ServingConfig(capacity=4, megabatch_size=2))
    for t in range(8):  # half the fleet spills
        churn.update(t, preds, target)
    churn.flush()
    assert any(t.spilled is not None for t in churn._tenants.values())
    churn.sync_async(reset_window=True).commit()
    assert all(t.spilled is None for t in churn._tenants.values())
    for t in range(8):  # every tenant restarts the new window from defaults
        np.testing.assert_allclose(np.asarray(churn.compute(t)), 0.0, atol=1e-6)
        break  # value check on one readmitted tenant is enough (compute flushes)


def test_streaming_wrappers_refuse_distributed_sync():
    sw = SlidingWindow(SumMetric(), 2)
    sw.update(1.0)
    sw.sync()  # distributed unavailable: no-op, exactly like Metric.sync
    assert not sw._is_synced
    sw.update(2.0)  # and updates keep working
    with pytest.raises(TorchMetricsUserError):
        sw.sync(distributed_available=lambda: True)
    ed = ExponentialDecay(SumMetric(), decay=0.5)
    ed.update(1.0)
    with pytest.raises(TorchMetricsUserError):
        ed.sync(distributed_available=lambda: True)


def test_async_handle_failed_commit_not_locked():
    """A failed commit leaves the handle uncommitted: retrying re-raises the
    REAL error, never a misleading 'already ran'."""
    rng = np.random.default_rng(23)
    coll = _mk_coll()
    _feed(coll, rng)
    flaky = FlakyGather(
        inner=SimWorld([_freeze_states(coll), _freeze_states(_remote_coll())]), fail_times=10
    )
    handle = coll.sync(async_=True, distributed_available=lambda: True, dist_sync_fn=flaky)
    with pytest.raises(TransientRuntimeError):
        handle.commit()
    assert not handle.committed
    with pytest.raises(TransientRuntimeError):  # the real error again, not "already ran"
        handle.commit()


@pytest.mark.serving
def test_engine_sync_async_rejects_bare_mean_states():
    eng = ServingEngine(_MeanTagMetric(), ServingConfig(capacity=4, megabatch_size=2))
    with pytest.raises(TorchMetricsUserError):
        eng.sync_async()


# ------------------------------------------------------------- drift monitor


def test_drift_monitor_breach_and_slo_namespace():
    rules = (
        obs.SloRule(name="drift_watch", expr="drift('acc_drift') > 0.5",
                    window=60.0, cooldown=0.0, severity="critical"),
    )
    with obs.telemetry_session(obs.TelemetryConfig(slo_rules=rules, slo_eval_on_sync=False)) as rec:
        dm = DriftMonitor(
            MeanMetric(), reference_window=4, test_window=2, threshold=0.5,
            name="acc_drift", eval_every=1,
        )
        for v in [1.0, 1.0, 1.0, 1.0]:  # fills the reference block
            dm.update(v)
        assert dm.reference_value is not None
        for v in [1.0, 1.0]:
            dm.update(v)
        assert dm.last is not None and not dm.breached  # no drift yet
        for v in [9.0, 9.0]:
            dm.update(v)
        # the second 9.0 is the 8th update: the reference block ROLLED to
        # mean(1,1,9,9)=5.0 right before the evaluation, so score = 9 - 5
        assert dm.breached and dm.last["score"] == pytest.approx(4.0)
        assert rec.drift_score("acc_drift") == pytest.approx(4.0)
        alerts = rec.evaluate_slos()
        assert any(a["rule"] == "drift_watch" and a["kind"] == "breach" for a in alerts)
    snap = rec.counters.snapshot()
    assert snap["drift_evals"] >= 4
    assert snap["drift_breaches"] >= 2
    drift_alerts = [e for e in rec.events_of("alert") if e.tag == "drift"]
    assert drift_alerts and drift_alerts[0].payload["kind"] == "drift"


def test_drift_monitor_rolling_reference_and_reset():
    dm = DriftMonitor(SumMetric(), reference_window=3, test_window=2, threshold=0.1,
                      eval_every=0)  # manual evaluation only
    assert dm.evaluate() is None  # no reference yet
    for v in [1.0, 1.0, 1.0]:
        dm.update(v)
    ref1 = float(np.asarray(dm.reference_value))
    assert ref1 == pytest.approx(3.0)
    for v in [2.0, 2.0, 2.0]:
        dm.update(v)  # second block replaces the reference
    assert float(np.asarray(dm.reference_value)) == pytest.approx(6.0)
    out = dm.evaluate()
    assert out["breached"]
    dm.reset()
    assert dm.reference_value is None and dm.last is None


# --------------------------------------------- version-skew mailbox degradation


def test_coalesce_version_is_bumped_for_streaming_counters():
    assert C._VERSION == 11  # v11: telemetry history plane (history_folds/burn_alerts)
    # the streaming counters are real fields of the piggybacked vector
    for f in ("window_rolls", "window_rotations", "async_syncs", "async_sync_wait_us",
              "drift_evals", "drift_breaches", "serve_rejected"):
        assert f in obs.COUNTER_FIELDS
    # every window tier's dispatch latency kind rides the fleet histogram vector
    for kind in ("wupdate", "wdual", "wstack"):
        assert kind in obs.FLEET_HISTOGRAM_KINDS


def test_window_latency_rides_fleet_vector():
    from torchmetrics_tpu.observability import histograms as H

    with obs.telemetry_session() as rec:
        sw = SlidingWindow(SumMetric(), 3, tier="ring")
        for x in range(5):
            sw.update(float(x))
        dual = SlidingWindow(SumMetric(), 3)  # auto: dual
        dual.update(1.0)
        stack = SlidingWindow(MaxMetric(), 3)  # auto: two_stack
        stack.update(1.0)
        vec = rec.histograms.fleet_vector()
    kinds = H.decode_fleet_vector(vec)
    assert kinds["wupdate"].count == 5
    assert kinds["wdual"].count == 1
    assert kinds["wstack"].count == 1


def test_mixed_version_rows_degrade_to_local_rollup():
    """A rank decoding another layout version's metadata row must fall back
    (lockstep per-leaf) and deposit NO mailbox rows — fleet rollups then
    degrade to a fresh collective / local rollup instead of misdecoding."""
    state = {"s": jnp.ones((3,), jnp.float32)}
    reds = {"s": "sum"}
    meta = C.build_local_metadata([state], [reds])

    skewed = np.array(meta)
    skewed[1] = C._VERSION - 1  # a v4 rank's row (same length, older version)

    def skew_world(value, group=None):
        v = np.asarray(value)
        if v.dtype.kind == "i" and v.ndim == 1 and v.size >= 4 and int(v[0]) == 0x436F414C:
            return [jnp.asarray(skewed), jnp.asarray(skewed)]
        return [jnp.asarray(value), jnp.asarray(value)]  # per-leaf fallback rows

    with obs.telemetry_session() as rec:
        C.clear_fleet_mailbox()
        with pytest.raises(C.CoalesceFallback):
            C.coalesced_process_sync([state], [reds], dist_sync_fn=skew_world)
        assert C.fleet_counter_rows() is None  # nothing deposited
        assert C.fleet_histogram_rows() is None
        # end to end: process_sync degrades to the per-leaf plane and still syncs
        out = S.process_sync(state, reds, dist_sync_fn=skew_world)
        np.testing.assert_array_equal(np.asarray(out["s"]), 2 * np.ones(3))
        # the rollup degrades to a local (1-rank) fleet view, never misdecodes
        fleet = obs.gather_counters()
        assert fleet.ranks == 1
    # a TRUNCATED older-layout row (shorter counter tail) also falls back
    short = np.array(meta)[:-4]

    def short_world(value, group=None):
        return [jnp.asarray(short), jnp.asarray(short)]

    with pytest.raises(C.CoalesceFallback):
        C.coalesced_process_sync([state], [reds], dist_sync_fn=short_world)


# ------------------------------------------------------------- trace rendering


def test_trace_report_renders_streaming_kinds(tmp_path):
    trace = tmp_path / "trace.jsonl"
    events = [
        {"kind": "window_roll", "metric": "MulticlassAccuracy#0", "tag": "wupdate",
         "timestamp": 1.0, "payload": {"window": 4, "filled": 4}},
        {"kind": "window_roll", "metric": "MulticlassAccuracy#0", "tag": "wupdate",
         "timestamp": 2.0, "payload": {"window": 4, "filled": 4}},
        {"kind": "async_sync", "metric": "MetricCollection.sync", "tag": "sync",
         "timestamp": 3.0, "duration_s": 0.08,
         "payload": {"wait_s": 0.02, "overlap_pct": 75.0, "payload_bytes": 128,
                     "collectives": 3, "fallback": False}},
        {"kind": "serve_rejected", "metric": "MulticlassAccuracy#1", "tag": "admission",
         "timestamp": 4.0, "payload": {"tenant": "'u1'"}},
    ]
    with open(trace, "w") as fh:
        for ev in events:
            fh.write(json.dumps(ev) + "\n")
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(os.path.dirname(__file__), "..", "tools", "trace_report.py")
    )
    trace_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_report)
    report = trace_report.aggregate(trace_report.load_events(str(trace)))
    s = report["streaming"]
    assert s["window_wraps"] == 2
    assert s["async_syncs"] == 1
    assert s["mean_overlap_pct"] == pytest.approx(75.0)
    assert s["serve_rejected"] == 1
    rendered = trace_report.render_table(report)
    assert "2 window wraps" in rendered and "1 async syncs" in rendered
    assert "mean overlap 75.0%" in rendered and "admission-rejected batches: 1" in rendered


# --------------------------------------------------------------- handle basics


def test_async_handle_bare_usage_and_result():
    state = {"s": jnp.asarray([1.0, 2.0], jnp.float32)}
    handle = AsyncSyncHandle([state], [{"s": "sum"}])  # world of one: identity fold
    synced = handle.result()
    np.testing.assert_array_equal(np.asarray(synced[0]["s"]), np.asarray(state["s"]))
    out = handle.commit()
    assert out is synced
    assert handle.overlap_pct >= 0.0


# ------------------------------------------------- tiered windows (ISSUE 12)


class IntCountMetric(Metric):
    """int32 'sum' state — exercises the dual/two-stack accumulator dtype
    policy (integer sum/mean leaves promote so long windows can't saturate)."""

    def __init__(self):
        super().__init__()
        self.add_state("n", default=np.zeros((), np.int32), dist_reduce_fx="sum")

    def _batch_state(self, x):
        return {"n": jnp.asarray(x, jnp.int32).sum()}

    def _compute(self, state):
        return state["n"]


class CallableReduceMetric(Metric):
    """Callable (semigroup) reduction — lands in the two-stack tier."""

    def __init__(self):
        super().__init__()
        self.add_state("prod", default=np.ones(()), dist_reduce_fx=lambda s: jnp.prod(s, axis=0))

    def _batch_state(self, x):
        return {"prod": jnp.asarray(x, jnp.float32)}

    def _compute(self, state):
        return state["prod"]


def test_window_tier_selection_pinned():
    """The reduce-tag → tier derivation (the same one graftlint's matrix
    performs statically): sum/mean/None → dual; max/min/callable semigroups →
    two_stack; custom merge / list-cat states → ring."""
    from torchmetrics_tpu.metric import window_tier

    assert window_tier(SumMetric()) == "dual"
    assert window_tier(MeanMetric()) == "dual"
    assert window_tier(MeanSquaredError()) == "dual"
    assert window_tier(MulticlassAccuracy(num_classes=5, validate_args=False)) == "dual"
    assert window_tier(MulticlassConfusionMatrix(num_classes=5, validate_args=False)) == "dual"
    assert window_tier(IntCountMetric()) == "dual"
    assert window_tier(MaxMetric()) == "two_stack"
    assert window_tier(MinMetric()) == "two_stack"
    assert window_tier(CallableReduceMetric()) == "two_stack"
    assert window_tier(CatMetric()) == "ring"          # list ("cat") state
    assert window_tier(LastValueMetric()) == "ring"    # custom _merge
    # the wrapper reports the chosen tier per metric
    assert SlidingWindow(MaxMetric(), 8).tier == "two_stack"
    assert SlidingWindow(CatMetric(), 8).tier == "ring"
    # an explicit pane is a granularity request: it forces the paned tier
    # (pane=1 == exact per-update sliding) instead of being silently dropped
    sw = SlidingWindow(SumMetric(), 8, pane=1)
    assert sw.tier == "two_stack" and sw.pane == 1
    with pytest.raises(ValueError):
        SlidingWindow(SumMetric(), 8, tier="dual", pane=1)  # pane is two-stack-only
    with pytest.raises(TorchMetricsUserError):
        SlidingWindow(CatMetric(), 8, pane=1)  # ring-only metric cannot take a pane


def test_window_tier_forced_rejections():
    with pytest.raises(TorchMetricsUserError):
        SlidingWindow(MaxMetric(), 4, tier="dual")  # max cannot fold in the pair
    with pytest.raises(TorchMetricsUserError):
        SlidingWindow(LastValueMetric(), 4, tier="two_stack")  # custom merge
    with pytest.raises(TorchMetricsUserError):
        SlidingWindow(CatMetric(), 4, tier="dual")  # list states need the ring
    with pytest.raises(ValueError):
        SlidingWindow(SumMetric(), 4, tier="bogus")
    with pytest.raises(ValueError):
        SlidingWindow(MaxMetric(), 4, tier="two_stack", pane=0)
    # forcing ring anywhere is always legal (the exact-trailing-N opt-in)
    assert SlidingWindow(MaxMetric(), 4, tier="ring").tier == "ring"


def test_window_parity_callable_reduction_two_stack():
    """Callable semigroup folds ride the two-stack tier in stream order."""
    sw = SlidingWindow(CallableReduceMetric(), 4, pane=2)
    assert sw.tier == "two_stack"
    vals = [1.5, 2.0, 0.5, 3.0, 1.25, 0.8, 2.5]
    for v in vals:
        sw.update(v)
    cov = sw.covered_updates()
    expect = float(np.prod(vals[-cov:]))
    np.testing.assert_allclose(float(np.asarray(sw.compute())), expect, rtol=1e-6)


def test_window_dual_accumulator_dtype_policy():
    """ISSUE 12 dtype fix: integer sum/mean leaves promote in the dual/
    two-stack accumulators (f32 under x64-off — exact below 2^24) so a long
    window cannot silently saturate int32; the fold's closed form stays
    exact. The ring keeps the metric's own integer dtype (one update's
    contribution per bucket never accumulates)."""
    sw = SlidingWindow(IntCountMetric(), 5)
    assert sw.tier == "dual"
    for _ in range(12):
        sw.update(np.full((3,), 1, np.int32))
    leaf = sw._wstate["n"]
    assert leaf.dtype == jnp.float32  # promoted pair (x64 off in tier-1 runs)
    cov = sw.covered_updates()
    assert float(np.asarray(sw.compute())) == 3.0 * cov  # closed form, exact
    stack = SlidingWindow(IntCountMetric(), 6, tier="two_stack", pane=2)
    for _ in range(9):
        stack.update(np.full((2,), 1, np.int32))
    assert stack._wstate["n"].dtype == jnp.float32
    assert float(np.asarray(stack.compute())) == 2.0 * stack.covered_updates()
    ring = SlidingWindow(IntCountMetric(), 4, tier="ring")
    ring.update(np.full((2,), 1, np.int32))
    assert ring._ring["n"].dtype == jnp.int32  # per-bucket contributions: no growth


def test_window_state_memory_window_independent():
    """The memory model the 100k bench gates: dual and two-stack state bytes
    do not depend on the window length; the ring's do."""
    mk = lambda: MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)
    dual_small = SlidingWindow(mk(), 1_000).state_memory()["total_bytes"]
    dual_big = SlidingWindow(mk(), 100_000).state_memory()["total_bytes"]
    assert dual_small == dual_big
    stack_small = SlidingWindow(MaxMetric(), 1_000).state_memory()["total_bytes"]
    stack_big = SlidingWindow(MaxMetric(), 100_000).state_memory()["total_bytes"]
    assert stack_small == stack_big
    ring_small = SlidingWindow(mk(), 8, tier="ring")
    ring_big = SlidingWindow(mk(), 64, tier="ring")
    p, t = _cls_batches(np.random.default_rng(0), 1)[0]
    ring_small.update(p, t)
    ring_big.update(p, t)
    assert ring_big.state_memory()["total_bytes"] > ring_small.state_memory()["total_bytes"]


@pytest.mark.aot
def test_window_dual_aot_warm_start(tmp_path):
    """AOT warm start for the new tags: a second 'boot' serves the first
    wdual/wstack dispatch from the serialized-executable cache, and the
    warm values match the cold path bitwise."""
    from torchmetrics_tpu import aot

    mk = lambda: MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)
    rng = np.random.default_rng(31)
    batches = _cls_batches(rng, 6)
    aot.enable(config=aot.AotConfig(cache_dir=str(tmp_path / "cache"), write_on_miss=True))
    cold = SlidingWindow(mk(), 4)
    stack_cold = SlidingWindow(MaxMetric(), 4, pane=2)
    for p, t in batches:
        cold.update(p, t)
        stack_cold.update(float(np.asarray(p).sum()))
    cold_value = np.asarray(cold.compute())
    aot.disable()
    aot.enable(config=aot.AotConfig(cache_dir=str(tmp_path / "cache")))  # fresh "boot"
    with obs.telemetry_session() as rec:
        warm = SlidingWindow(mk(), 4)
        stack_warm = SlidingWindow(MaxMetric(), 4, pane=2)
        for p, t in batches:
            warm.update(p, t)
            stack_warm.update(float(np.asarray(p).sum()))
    aot.disable()
    snap = rec.counters.snapshot()
    assert snap["aot_cache_hits"] >= 2  # one wdual + one wstack load
    for tag in (".wdual", ".wstack"):
        keys = {k: v for k, v in snap.per_key.items() if k.endswith(tag)}
        assert sum(v["compiles"] for v in keys.values()) == 0, tag
        assert sum(v["aot_hits"] for v in keys.values()) == 1, tag
    np.testing.assert_array_equal(np.asarray(warm.compute()), cold_value)


# ------------------------------------------- windowed tenants (ServingEngine)


@pytest.mark.serving
def test_windowed_serving_parity_one_compile_and_rotations():
    """ServingConfig(window=): every tenant gets a dual window inside the
    stacked pytree; per-tenant values satisfy the covered-span oracle, ONE
    vwupdate compile serves the fleet, compute_all folds windows vmapped,
    and rotation accounting reaches the telemetry counters."""
    rng = np.random.default_rng(41)
    mk = lambda: MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
    streams = {
        t: [_serve_batch(rng) for _ in range(9)] for t in range(12)
    }
    with obs.telemetry_session() as rec:
        eng = ServingEngine(mk(), ServingConfig(capacity=16, megabatch_size=4, window=3))
        for i in range(9):
            for t in range(12):
                eng.update(t, *streams[t][i])
        eng.flush()
        for t in range(12):
            cov = eng.covered_updates(t)
            assert 3 <= cov < 6  # dual hop: window <= covered < 2*window
            plain = mk()
            for b in streams[t][-cov:]:
                plain.update(*b)
            np.testing.assert_allclose(
                np.asarray(eng.compute(t)), np.asarray(plain.compute()), rtol=1e-6
            )
        vals = eng.compute_all()
        for t in range(12):
            np.testing.assert_allclose(
                np.asarray(vals[t]), np.asarray(eng.compute(t)), rtol=1e-6
            )
    snap = rec.counters.snapshot()
    vw = {k: v for k, v in snap.per_key.items() if k.endswith(".vwupdate")}
    assert sum(v["compiles"] for v in vw.values()) == 1
    assert snap["window_rolls"] == 12 * 9
    assert snap["window_rotations"] == 12 * 3  # each tenant rotated at 3, 6, 9
    s = eng.summary()
    assert s["window"] == 3 and s["window_tier"] == "dual"
    assert s["window_rotations"] == 12 * 3


@pytest.mark.serving
def test_windowed_serving_two_stack_spill_and_checkpoint():
    """Two-stack windowed tenants survive LRU spill/readmit and checkpoint
    round-trips (window-layout leaves ride the same host copies)."""
    mk = MaxMetric
    rng = np.random.default_rng(43)
    eng = ServingEngine(
        mk(), ServingConfig(capacity=4, megabatch_size=2, window=6,
                            window_tier="two_stack", window_pane=2)
    )
    assert eng.summary()["window_tier"] == "two_stack"
    vals = {t: [float(rng.normal()) for _ in range(13)] for t in range(8)}
    for i in range(13):
        for t in range(8):
            eng.update(t, vals[t][i])
    eng.flush()
    assert any(t.spilled is not None for t in eng._tenants.values())
    for t in range(8):
        cov = eng.covered_updates(t)
        assert cov >= 6
        expect = max(vals[t][-cov:])
        np.testing.assert_allclose(np.asarray(eng.compute(t)), expect, rtol=1e-6)
    before = np.asarray(eng.compute(5))
    sd = eng.state_dict(5)
    eng.reset(5)
    np.testing.assert_allclose(np.asarray(eng.compute(5)), MaxMetric().compute())
    eng.load_state_dict(5, sd)
    np.testing.assert_array_equal(np.asarray(eng.compute(5)), before)


@pytest.mark.serving
def test_windowed_serving_rejections_and_contracts():
    mk = lambda: MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
    with pytest.raises(TorchMetricsUserError):
        ServingEngine(CatMetric(), ServingConfig(window=4))  # ring-only tier
    with pytest.raises(ValueError):
        ServingConfig(window=0)
    with pytest.raises(ValueError):
        ServingConfig(window=4, window_tier="ring")
    eng = ServingEngine(mk(), ServingConfig(capacity=4, megabatch_size=2, window=4))
    rng = np.random.default_rng(44)
    eng.update(0, *_serve_batch(rng))
    eng.flush()
    with pytest.raises(TorchMetricsUserError):
        eng.sync_async()  # windowed stacks have no defined cross-rank row fold
    # a windowed checkpoint refuses to load into a differently-shaped engine
    plain = ServingEngine(mk(), ServingConfig(capacity=4, megabatch_size=2))
    plain.update(0, *_serve_batch(rng))
    plain.flush()
    with pytest.raises(TorchMetricsUserError):
        eng.load_state_dict(1, plain.state_dict(0))


@pytest.mark.serving
def test_windowed_serving_quarantine_isolates_offender():
    """Engine-level fault isolation works unchanged under vwupdate: a
    poisoned megabatch rolls back and only the offender is quarantined."""
    mk = lambda: MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
    rng = np.random.default_rng(45)
    eng = ServingEngine(
        mk(), ServingConfig(capacity=8, megabatch_size=4, on_error="quarantine", window=3)
    )
    batch = _serve_batch(rng)
    boom = {"armed": False}

    def hook(tids):
        if boom["armed"] and 2 in tids:  # fails the megabatch AND the re-drive
            raise RuntimeError("poisoned tenant")

    for t in range(4):
        eng.update(t, *batch)
    eng.flush()
    eng._fault_hook = hook
    boom["armed"] = True
    for t in range(4):
        eng.update(t, *batch)
    eng.flush()
    eng._fault_hook = None
    roster = eng.tenants()
    assert roster[2]["quarantined"]
    for t in (0, 1, 3):
        assert not roster[t]["quarantined"]
        assert eng._tenants[t].update_count == 2


@pytest.mark.serving
def test_windowed_serving_ragged_phase_parity():
    """Tenants at DIFFERENT window phases inside one vmapped megabatch: the
    branch-free rotation/flip selection is per-row, so a dispatch that
    rotates tenant A's block (or flips its two-stack) while tenant B is
    mid-block must keep both exact. Ragged traffic drives every phase."""
    rng = np.random.default_rng(77)
    for cfg_kw, mk in [({}, SumMetric),
                       ({"window_tier": "two_stack", "window_pane": 2}, SumMetric),
                       ({}, MaxMetric)]:
        eng = ServingEngine(mk(), ServingConfig(capacity=16, megabatch_size=4, window=5, **cfg_kw))
        streams = {t: [] for t in range(10)}
        for i in range(23):
            for t in range(10):
                if (i + t) % (t % 3 + 1) == 0:  # tenant-dependent cadence
                    v = float(rng.normal())
                    streams[t].append(v)
                    eng.update(t, v)
        eng.flush()
        for t in range(10):
            cov = eng.covered_updates(t)
            plain = mk()
            for v in streams[t][-cov:] if cov else []:
                plain.update(v)
            np.testing.assert_allclose(
                np.asarray(eng.compute(t)), np.asarray(plain.compute()), rtol=1e-5
            )
        vals = eng.compute_all()
        for t in range(10):
            np.testing.assert_allclose(
                np.asarray(vals[t]), np.asarray(eng.compute(t)), rtol=1e-6
            )
