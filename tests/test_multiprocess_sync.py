"""TRUE multi-process sync: a real 2-process JAX CPU cluster, not a fake gather.

The reference's DDP tests run a 2-worker gloo pool (conftest.py:75-83); until
now our plane-2 coverage injected fake gathers. Here two OS processes form an
actual ``jax.distributed`` cluster (gloo CPU collectives over a localhost
coordinator) and each updates metrics with its own shard; ``compute()`` then
syncs through the production ``process_sync``/``gather_all_arrays`` path and
every process must report the global value.

JAX_PLATFORMS must be set before interpreter start (sitecustomize registers the
TPU plugin at startup), so workers are spawned with a prepared environment.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_WORKER = textwrap.dedent(
    """
    import json, sys
    import jax

    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc

    import jax.numpy as jnp
    import numpy as np
    import torchmetrics_tpu as tm

    rng = np.random.default_rng(42)  # same stream everywhere; shard by slicing
    preds = rng.normal(size=(48, 5)).astype(np.float32)
    target = rng.integers(0, 5, 48).astype(np.int32)
    shard = 48 // nproc
    lo, hi = pid * shard, (pid + 1) * shard

    out = {}

    acc = tm.MulticlassAccuracy(5, average="micro")
    acc.update(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
    out["acc"] = float(acc.compute())  # sync_on_compute -> plane-2 process gather

    confmat = tm.MulticlassConfusionMatrix(5)
    confmat.update(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
    out["confmat"] = np.asarray(confmat.compute()).tolist()

    # concat state with UNEVEN per-process counts: plane-2 gathers lengths first,
    # pads to the max and trims (reference utilities/distributed.py:130-147)
    cat = tm.CatMetric()
    n_take = shard if pid == 0 else shard - 7  # uneven on purpose
    cat.update(jnp.asarray(preds[lo : lo + n_take, 0]))
    out["cat_sorted"] = sorted(np.asarray(cat.compute()).reshape(-1).tolist())

    # unsync restores the local view after the synced compute
    acc.sync()
    acc.unsync()
    local_only = tm.MulticlassAccuracy(5, average="micro", sync_on_compute=False)
    local_only.update(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
    out["acc_local"] = float(local_only.compute())

    # a process with ZERO updates must still participate in the collectives
    empty_cat = tm.CatMetric()
    if pid == 0:
        empty_cat.update(jnp.asarray(preds[:4, 1]))
    out["empty_cat_sorted"] = sorted(np.asarray(empty_cat.compute()).reshape(-1).tolist())

    # dist_sync_on_step: forward returns the cross-PROCESS-synced value each step
    step_synced = tm.MulticlassAccuracy(5, average="micro", dist_sync_on_step=True)
    out["acc_step_synced"] = float(step_synced(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi])))

    # a "mean"-reduced state: the n-way fold must be mean-of-stack, not pairwise
    class MeanState(tm.Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("m", default=np.zeros(()), dist_reduce_fx="mean")

        def _batch_state(self, x):
            return {"m": x.mean()}

        def _compute(self, state):
            return state["m"]

    ms = MeanState()
    ms.update(jnp.asarray(np.float32(pid + 1.0) * jnp.ones(4)))
    out["mean_state"] = float(ms.compute())

    # fault-injected sync (reliability layer): every rank's first gather raises a
    # transient participant-drop BEFORE entering the collective (deterministic and
    # rank-symmetric, so the cluster retries in lockstep); the RetryPolicy re-runs
    # process_sync through the REAL gather_all_arrays and the recovered value must
    # equal the global one
    from torchmetrics_tpu.reliability import FlakyGather, ReliabilityConfig, RetryPolicy

    flaky = FlakyGather(fail_times=1)
    racc = tm.MulticlassAccuracy(
        5, average="micro", dist_sync_fn=flaky,
        reliability=ReliabilityConfig(retry=RetryPolicy(max_attempts=3, backoff_base=0.01)),
    )
    racc.update(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        out["acc_retry_sync"] = float(racc.compute())
    out["flaky_gather_failures"] = flaky.failures

    print("RESULT" + json.dumps(out))
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("world", [2, 3])
def test_process_cluster_sync(tmp_path, world):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)  # no virtual device splitting inside the cluster
    env["PYTHONPATH"] = os.path.join(os.path.dirname(__file__), "..") + os.pathsep + env.get("PYTHONPATH", "")
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), str(world), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for i in range(world)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=280)
        assert p.returncode == 0, out[-3000:]
        payload = [line for line in out.splitlines() if line.startswith("RESULT")]
        assert payload, out[-3000:]
        outs.append(json.loads(payload[-1][len("RESULT"):]))

    # single-process ground truth over the full data
    import jax.numpy as jnp

    import torchmetrics_tpu as tm

    rng = np.random.default_rng(42)
    preds = rng.normal(size=(48, 5)).astype(np.float32)
    target = rng.integers(0, 5, 48).astype(np.int32)
    ref_acc = tm.MulticlassAccuracy(5, average="micro")
    ref_acc.update(jnp.asarray(preds), jnp.asarray(target))
    ref_confmat = tm.MulticlassConfusionMatrix(5)
    ref_confmat.update(jnp.asarray(preds), jnp.asarray(target))

    for pid, res in enumerate(outs):
        np.testing.assert_allclose(res["acc"], float(ref_acc.compute()), atol=1e-7, err_msg=f"proc {pid}")
        np.testing.assert_allclose(
            res["acc_step_synced"], float(ref_acc.compute()), atol=1e-7, err_msg=f"proc {pid} dist_sync_on_step"
        )
        np.testing.assert_allclose(
            np.asarray(res["confmat"]), np.asarray(ref_confmat.compute()), err_msg=f"proc {pid}"
        )
        shard = 48 // world
        expected_cat = sorted(
            x for p in range(world)
            for x in preds[p * shard : p * shard + (shard if p == 0 else shard - 7), 0].tolist()
        )
        np.testing.assert_allclose(res["cat_sorted"], expected_cat, atol=1e-7, err_msg=f"proc {pid}")
        np.testing.assert_allclose(
            res["empty_cat_sorted"], sorted(preds[:4, 1].tolist()), atol=1e-7,
            err_msg=f"proc {pid} zero-update participation",
        )
        # mean fold over n ranks: mean(1, 2, ..., world)
        np.testing.assert_allclose(
            res["mean_state"], np.mean(np.arange(1, world + 1)), atol=1e-6,
            err_msg=f"proc {pid} n-way mean fold",
        )
        # fault-injected sync: the transient participant drop was retried through
        # the real collective and the recovered value equals the global one
        assert res["flaky_gather_failures"] == 1, f"proc {pid} fault did not fire"
        np.testing.assert_allclose(
            res["acc_retry_sync"], float(ref_acc.compute()), atol=1e-7,
            err_msg=f"proc {pid} retried sync parity",
        )
    # per-process local values differ from the global (proves sync actually ran)
    assert outs[0]["acc_local"] != outs[1]["acc_local"] or outs[0]["acc_local"] != outs[0]["acc"]


_WORKER_COMPOSITE = textwrap.dedent(
    """
    import json, sys
    import jax

    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)

    import jax.numpy as jnp
    import numpy as np
    import torchmetrics_tpu as tm
    from torchmetrics_tpu import MetricCollection
    from torchmetrics_tpu.detection import MeanAveragePrecision
    from torchmetrics_tpu.wrappers import MinMaxMetric

    rng = np.random.default_rng(7)  # same stream everywhere; shard by slicing
    preds = rng.normal(size=(48, 5)).astype(np.float32)
    target = rng.integers(0, 5, 48).astype(np.int32)
    shard = 48 // nproc
    lo, hi = pid * shard, (pid + 1) * shard
    out = {}

    # MetricCollection with compute groups through plane-2 sync: every process
    # must see the GLOBAL value for every member
    coll = MetricCollection({
        "acc": tm.MulticlassAccuracy(5, average="micro"),
        "f1": tm.MulticlassF1Score(5, average="macro"),
        "auroc": tm.MulticlassAUROC(5, thresholds=16),
        "confmat": tm.MulticlassConfusionMatrix(5),
    })
    coll.update(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
    out["collection"] = {k: np.asarray(v).tolist() for k, v in coll.compute().items()}

    # wrapper: the child metric syncs at compute -> raw is global
    mm = MinMaxMetric(tm.MulticlassAccuracy(5, average="micro"))
    mm(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
    out["minmax_raw"] = float(mm.compute()["raw"])

    # detection: per-image list states with UNEVEN shapes across processes
    boxes = rng.uniform(0, 100, (12, 3, 2)).astype(np.float32)  # 12 imgs, 3 boxes
    wh = rng.uniform(5, 40, (12, 3, 2)).astype(np.float32)
    labels = rng.integers(0, 3, (12, 3)).astype(np.int32)
    scores = rng.uniform(0.1, 1, (12, 3)).astype(np.float32)
    per = 12 // nproc
    m = MeanAveragePrecision()
    d_preds, d_tgt = [], []
    for i in range(pid * per, (pid + 1) * per):
        nd = 3 if i % 2 == 0 else 2  # uneven per-image counts
        bb = np.concatenate([boxes[i, :nd], boxes[i, :nd] + wh[i, :nd]], -1)
        d_preds.append({"boxes": jnp.asarray(bb + rng.standard_normal(bb.shape).astype(np.float32)),
                        "scores": jnp.asarray(scores[i, :nd]), "labels": jnp.asarray(labels[i, :nd])})
        d_tgt.append({"boxes": jnp.asarray(bb), "labels": jnp.asarray(labels[i, :nd])})
    m.update(d_preds, d_tgt)
    out["map"] = float(m.compute()["map"])

    print("RESULT" + json.dumps(out))
    """
)


@pytest.mark.parametrize("world", [2])
def test_process_cluster_composite_sync(tmp_path, world):
    """Collections (compute groups), wrappers, and detection list states through
    the REAL plane-2 process gather — every process reports the global value."""
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER_COMPOSITE)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = os.path.join(os.path.dirname(__file__), "..") + os.pathsep + env.get("PYTHONPATH", "")
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), str(world), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for i in range(world)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=280)
        assert p.returncode == 0, out[-3000:]
        payload = [line for line in out.splitlines() if line.startswith("RESULT")]
        assert payload, out[-3000:]
        outs.append(json.loads(payload[-1][len("RESULT"):]))

    # one-process ground truth over the full data (same generator stream)
    import jax.numpy as jnp

    import torchmetrics_tpu as tm
    from torchmetrics_tpu import MetricCollection
    from torchmetrics_tpu.detection import MeanAveragePrecision

    rng = np.random.default_rng(7)
    preds = rng.normal(size=(48, 5)).astype(np.float32)
    target = rng.integers(0, 5, 48).astype(np.int32)
    ref = MetricCollection({
        "acc": tm.MulticlassAccuracy(5, average="micro"),
        "f1": tm.MulticlassF1Score(5, average="macro"),
        "auroc": tm.MulticlassAUROC(5, thresholds=16),
        "confmat": tm.MulticlassConfusionMatrix(5),
    })
    ref.update(jnp.asarray(preds), jnp.asarray(target))
    want = {k: np.asarray(v) for k, v in ref.compute().items()}

    boxes = rng.uniform(0, 100, (12, 3, 2)).astype(np.float32)
    wh = rng.uniform(5, 40, (12, 3, 2)).astype(np.float32)
    labels = rng.integers(0, 3, (12, 3)).astype(np.int32)
    scores = rng.uniform(0.1, 1, (12, 3)).astype(np.float32)
    # the workers consume their rng in shard order: replay pid-by-pid so the
    # jitter draws line up with each worker's stream
    ref_map = MeanAveragePrecision()
    per = 12 // world
    for pid in range(world):
        wrng = np.random.default_rng(7)
        wrng.normal(size=(48, 5))
        wrng.integers(0, 5, 48)
        wrng.uniform(0, 100, (12, 3, 2))
        wrng.uniform(5, 40, (12, 3, 2))
        wrng.integers(0, 3, (12, 3))
        wrng.uniform(0.1, 1, (12, 3))
        d_preds, d_tgt = [], []
        for i in range(pid * per, (pid + 1) * per):
            nd = 3 if i % 2 == 0 else 2
            bb = np.concatenate([boxes[i, :nd], boxes[i, :nd] + wh[i, :nd]], -1)
            d_preds.append({"boxes": jnp.asarray(bb + wrng.standard_normal(bb.shape).astype(np.float32)),
                            "scores": jnp.asarray(scores[i, :nd]), "labels": jnp.asarray(labels[i, :nd])})
            d_tgt.append({"boxes": jnp.asarray(bb), "labels": jnp.asarray(labels[i, :nd])})
        ref_map.update(d_preds, d_tgt)
    want_map = float(ref_map.compute()["map"])

    for pid, res in enumerate(outs):
        for key, val in want.items():
            np.testing.assert_allclose(
                np.asarray(res["collection"][key]), val, atol=1e-6, err_msg=f"proc {pid} collection {key}"
            )
        np.testing.assert_allclose(res["minmax_raw"], float(want["acc"]), atol=1e-7, err_msg=f"proc {pid} minmax")
        np.testing.assert_allclose(res["map"], want_map, atol=1e-7, err_msg=f"proc {pid} mAP")
