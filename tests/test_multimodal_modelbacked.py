"""Multimodal + model-backed text tests: CLIPScore/CLIP-IQA machinery with a toy
embedder, LVE oracle parity, BERTScore parity via the reference's own
user-model/user-tokenizer seam, and the offline gates."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from tests.helpers import _assert_allclose
from tests.oracle import reference_torchmetrics

import torchmetrics_tpu as tm
import torchmetrics_tpu.functional as F

_RNG = np.random.default_rng(21)
_EMB = _RNG.normal(size=(64, 12)).astype(np.float32)  # toy vocab embedding table


def _oracle():
    tm_ref = reference_torchmetrics()
    if tm_ref is None:
        pytest.skip("oracle unavailable")
    import torch

    return tm_ref, torch


# ----------------------------------------------------------------- CLIPScore

class ToyClip:
    """Deterministic toy CLIP: images hash to features via mean-pool projection,
    texts via summed token embeddings."""

    def get_image_features(self, images):
        flat = jnp.stack([jnp.asarray(i, jnp.float32).reshape(-1)[: 3 * 4] for i in images])
        return flat @ jnp.asarray(_EMB[: 3 * 4, :8])

    def get_text_features(self, texts):
        out = []
        for t in texts:
            ids = [hash(w) % 64 for w in t.split()]
            out.append(jnp.asarray(_EMB[ids, :8]).sum(axis=0))
        return jnp.stack(out)


def test_clip_score_machinery():
    imgs = [jnp.asarray(_RNG.random((3, 4, 4)).astype(np.float32)) for _ in range(3)]
    texts = ["a cat on a mat", "a dog", "the quick brown fox"]
    score = F.clip_score(imgs, texts, model_name_or_path=ToyClip())
    assert 0.0 <= float(score) <= 100.0
    # identical embeddings give the max score
    same = F.clip_score(texts, list(texts), model_name_or_path=ToyClip())
    assert float(same) == pytest.approx(100.0, abs=1e-3)

    metric = tm.CLIPScore(model_name_or_path=ToyClip())
    metric.update(imgs, texts)
    metric.update(imgs[:2], texts[:2])
    assert 0.0 <= float(metric.compute()) <= 100.0
    # running mean matches one-shot over the concatenation
    oneshot = F.clip_score(imgs + imgs[:2], texts + texts[:2], model_name_or_path=ToyClip())
    _assert_allclose(metric.compute(), np.maximum(np.asarray(oneshot), 0), atol=1e-4)


def test_clip_score_validation_and_gate():
    with pytest.raises(ValueError, match="same"):
        F.clip_score(["a"], ["a", "b"], model_name_or_path=ToyClip())
    with pytest.raises(ModuleNotFoundError, match="local HF cache|transformers"):
        tm.CLIPScore(model_name_or_path="openai/clip-vit-large-patch14")


def test_clip_iqa_machinery():
    m = tm.CLIPImageQualityAssessment(model_name_or_path=ToyClip(), prompts=("quality", ("Warm photo.", "Cold photo.")))
    m.update(jnp.asarray(_RNG.random((2, 3, 4, 4)).astype(np.float32)))
    out = m.compute()
    assert set(out) == {"quality", "user_defined_0"}  # reference numbers user prompts among themselves
    for v in out.values():  # per-image scores, reference shape semantics
        arr = np.asarray(v)
        assert arr.shape == (2,) and ((0.0 <= arr) & (arr <= 1.0)).all()
    with pytest.raises(ModuleNotFoundError, match="clip_iqa"):
        tm.CLIPImageQualityAssessment()


# ----------------------------------------------------------------------- LVE

def test_lve_parity():
    tm_ref, torch = _oracle()
    pred = _RNG.normal(size=(10, 100, 3)).astype(np.float32)
    gt = _RNG.normal(size=(12, 100, 3)).astype(np.float32)
    mouth = [0, 1, 2, 3, 4, 50, 51]
    ours = F.lip_vertex_error(jnp.asarray(pred), jnp.asarray(gt), mouth)
    ref = tm_ref.functional.multimodal.lip_vertex_error(torch.as_tensor(pred), torch.as_tensor(gt), mouth)
    _assert_allclose(ours, ref.numpy(), atol=1e-5)
    ours_m = tm.LipVertexError(mouth_map=mouth)
    from torchmetrics.multimodal.lve import LipVertexError as RefLVE  # type: ignore

    ref_m = RefLVE(mouth_map=mouth)
    for _ in range(2):
        ours_m.update(jnp.asarray(pred), jnp.asarray(gt))
        ref_m.update(torch.as_tensor(pred), torch.as_tensor(gt))
    _assert_allclose(ours_m.compute(), ref_m.compute().numpy(), atol=1e-5)


# ------------------------------------------------------------------ BERTScore

class ToyTokenizer:
    """Whitespace tokenizer over a fixed hashed vocab, with CLS=1 / SEP=2 / PAD=0."""

    def __call__(self, texts, padding=True, truncation=False, max_length=None, return_tensors="np"):
        rows = [[1] + [3 + (hash(w) % 60) for w in t.split()] + [2] for t in texts]
        if truncation and max_length:
            rows = [r[:max_length] for r in rows]
        width = max(len(r) for r in rows)
        input_ids = np.zeros((len(rows), width), np.int64)
        attention_mask = np.zeros((len(rows), width), np.int64)
        for i, r in enumerate(rows):
            input_ids[i, : len(r)] = r
            attention_mask[i, : len(r)] = 1
        if return_tensors == "pt":
            import torch

            return {"input_ids": torch.as_tensor(input_ids), "attention_mask": torch.as_tensor(attention_mask)}
        return {"input_ids": input_ids, "attention_mask": attention_mask}


def _jnp_embedder(input_ids, attention_mask):
    return np.asarray(_EMB)[np.asarray(input_ids)]


def _torch_embedder():
    import torch

    class M(torch.nn.Module):
        def forward(self, input_ids, attention_mask):
            return torch.from_numpy(_EMB)[input_ids]

    return M()


# lengths strictly ascending in BOTH lists: the reference length-sorts sentences and
# restores order with a double permutation that is only correct when the sort is the
# identity — aligned fixtures keep its scores pair-aligned for the comparison
PREDS = ["hello world", "the cat sat on mats", "a very quick brown fox jumps high"]
TARGET = ["hello there", "a cat sat on mats", "the quick brown fox jumped so high"]


@pytest.mark.parametrize("idf", [False, True])
def test_bert_score_parity_user_model(idf):
    tm_ref, torch = _oracle()
    ours = F.bert_score(PREDS, TARGET, model=_jnp_embedder, user_tokenizer=ToyTokenizer(), idf=idf)
    ref = tm_ref.functional.text.bert_score(
        PREDS, TARGET,
        model=_torch_embedder(),
        user_tokenizer=ToyTokenizer(),
        user_forward_fn=lambda model, batch: model(batch["input_ids"], batch["attention_mask"]),
        idf=idf,
    )
    for key in ("precision", "recall", "f1"):
        _assert_allclose(ours[key], np.asarray(ref[key]), atol=1e-4, msg=f"key={key} idf={idf}")


def test_bert_score_class_matches_functional():
    m = tm.BERTScore(model=_jnp_embedder, user_tokenizer=ToyTokenizer(), max_length=24)
    m.update(PREDS[:2], TARGET[:2])
    m.update(PREDS[2:], TARGET[2:])
    out = m.compute()
    direct = F.bert_score(PREDS, TARGET, model=_jnp_embedder, user_tokenizer=ToyTokenizer())
    for key in ("precision", "recall", "f1"):
        _assert_allclose(out[key], np.asarray(direct[key]), atol=1e-4, msg=key)


def test_bert_score_multi_reference_best_f1():
    multi = [["a cat sat on the mat", "completely unrelated words here"]]
    single = F.bert_score(["the cat sat on the mat"], ["a cat sat on the mat"],
                          model=_jnp_embedder, user_tokenizer=ToyTokenizer())
    best = F.bert_score(["the cat sat on the mat"], multi, model=_jnp_embedder, user_tokenizer=ToyTokenizer())
    _assert_allclose(best["f1"], np.asarray(single["f1"]), atol=1e-6)


def test_model_backed_gates():
    with pytest.raises(ModuleNotFoundError, match="local HF cache|transformers"):
        F.bert_score(PREDS, TARGET, model_name_or_path="roberta-large")
    with pytest.raises(ModuleNotFoundError, match="local HF cache|transformers"):
        tm.InfoLM()
    with pytest.raises(ModuleNotFoundError, match="vmaf"):
        tm.VideoMultiMethodAssessmentFusion()
    with pytest.raises(ModuleNotFoundError, match="baseline"):
        F.bert_score(PREDS, TARGET, model=_jnp_embedder, user_tokenizer=ToyTokenizer(), rescale_with_baseline=True)
