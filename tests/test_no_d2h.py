"""No device→host transfers in construction/update hot paths.

On tunneled TPU runtimes a single D2H readback (an ``np.asarray`` of a device array,
or jit lowering a closure-captured *device* constant) permanently flips the process
into synchronous per-call dispatch (~80x slower per call). The contract enforced
here: metric construction, ``update`` (first call included — lowering embeds
closure constants), and ``forward`` perform **zero** device→host transfers. Only
``compute()`` — the value handoff to the user — may read back.

``jax.transfer_guard_device_to_host("disallow")`` turns any violation into an error,
on every platform, so this guards the TPU behavior from a CPU test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import NUM_DEVICES


@pytest.fixture()
def guard():
    with jax.transfer_guard_device_to_host("disallow"):
        yield


def _cls_batch(n=256, c=5, seed=0):
    rng = np.random.default_rng(seed)
    preds = jnp.asarray(rng.normal(size=(n, c)).astype(np.float32))
    probs = jax.nn.softmax(preds)
    target = jnp.asarray(rng.integers(0, c, n, dtype=np.int32))
    return preds, probs, target


class TestNoD2HOnUpdate:
    def test_stat_scores_family(self, guard):
        from torchmetrics_tpu.classification import (
            BinaryF1Score,
            MulticlassAccuracy,
            MulticlassF1Score,
            MultilabelAccuracy,
        )

        preds, probs, target = _cls_batch()
        for m in (
            MulticlassAccuracy(5, average="micro", validate_args=False),
            MulticlassF1Score(5, average="macro", validate_args=False),
        ):
            m.update(preds, target)
            m.update(preds, target)
        b = BinaryF1Score(validate_args=False)
        b.update(probs[:, 0], (target > 2).astype(jnp.int32))
        ml = MultilabelAccuracy(num_labels=5, validate_args=False)
        ml.update(probs, (probs > 0.2).astype(jnp.int32))

    def test_curve_family_binned(self, guard):
        from torchmetrics_tpu.classification import (
            BinaryAUROC,
            MulticlassAUROC,
            MulticlassAveragePrecision,
            MulticlassCalibrationError,
            MulticlassConfusionMatrix,
        )

        preds, probs, target = _cls_batch()
        for m in (
            MulticlassAUROC(5, thresholds=100, validate_args=False),
            MulticlassAveragePrecision(5, thresholds=50, validate_args=False),
            MulticlassConfusionMatrix(5, validate_args=False),
            MulticlassCalibrationError(5, n_bins=15, validate_args=False),
        ):
            m.update(probs, target)
            m.update(probs, target)
        b = BinaryAUROC(thresholds=100, validate_args=False)
        b.update(probs[:, 0], (target > 2).astype(jnp.int32))

    def test_aggregation_and_regression(self, guard):
        from torchmetrics_tpu.aggregation import MaxMetric, MeanMetric, MinMetric, SumMetric
        from torchmetrics_tpu.regression import MeanSquaredError, PearsonCorrCoef

        x = jnp.asarray(np.random.default_rng(1).random(128).astype(np.float32))
        for m in (MaxMetric(), MinMetric(), SumMetric(), MeanMetric()):
            m.update(x)
            m.update(x * 2)
        mse = MeanSquaredError()
        mse.update(x, x * 1.1)
        p = PearsonCorrCoef()
        p.update(x, x * 0.5 + 0.1)

    def test_forward_path(self, guard):
        from torchmetrics_tpu.classification import MulticlassAccuracy

        preds, _, target = _cls_batch()
        m = MulticlassAccuracy(5, average="micro", validate_args=False)
        val = m.forward(preds, target)
        val2 = m(preds, target)
        assert val is not None and val2 is not None

    def test_fused_collection_update(self, guard):
        from torchmetrics_tpu import MetricCollection
        from torchmetrics_tpu.classification import (
            MulticlassAccuracy,
            MulticlassAUROC,
            MulticlassConfusionMatrix,
            MulticlassF1Score,
        )

        _, probs, target = _cls_batch(c=10)
        pure = MetricCollection({
            "acc": MulticlassAccuracy(10, average="micro", validate_args=False),
            "f1": MulticlassF1Score(10, average="macro", validate_args=False),
            "auroc": MulticlassAUROC(10, thresholds=64, validate_args=False),
            "confmat": MulticlassConfusionMatrix(10, validate_args=False),
        }).as_pure()
        step = jax.jit(pure.update, donate_argnums=0)
        states = pure.init()
        for _ in range(2):
            states = step(states, probs, target)
        jax.block_until_ready(states)

    def test_fid_update(self, guard):
        from torchmetrics_tpu.image import FrechetInceptionDistance

        class Toy:
            num_features = 8

            def __call__(self, imgs):
                return jnp.reshape(jnp.asarray(imgs, jnp.float32), (imgs.shape[0], -1))[:, :8]

        fid = FrechetInceptionDistance(feature=Toy(), normalize=True)
        imgs = jnp.asarray(np.random.default_rng(2).random((4, 3, 8, 8)).astype(np.float32))
        fid.update(imgs, real=True)
        fid.update(imgs, real=False)
        jax.block_until_ready(fid._state)

    def test_padded_detection_update(self, guard):
        from torchmetrics_tpu.detection import PaddedDetectionAccumulator

        acc = PaddedDetectionAccumulator(capacity_images=4, max_detections=4, max_groundtruths=4)
        state = acc.init()
        batch = tuple(
            jnp.zeros(s, d)
            for s, d in (
                ((2, 4, 4), jnp.float32), ((2, 4), jnp.float32), ((2, 4), jnp.int32), ((2,), jnp.int32),
                ((2, 4, 4), jnp.float32), ((2, 4), jnp.int32), ((2, 4), jnp.int32), ((2, 4), jnp.float32),
                ((2,), jnp.int32),
            )
        )
        state = jax.jit(acc.update)(state, *batch)
        jax.block_until_ready(state)

    def test_reset_and_reuse(self, guard):
        from torchmetrics_tpu.classification import MulticlassAccuracy

        preds, _, target = _cls_batch()
        m = MulticlassAccuracy(5, average="micro", validate_args=False)
        m.update(preds, target)
        m.reset()
        m.update(preds, target)


@pytest.mark.telemetry
class TestTelemetryD2HContract:
    """The observability layer's two-sided contract with this file's invariant:
    enabled telemetry must not ADD readbacks to the hot loop (signatures, clocks
    and counters are host metadata), and its d2h counter must agree with the
    transfer guard that the instrumented loop performed zero."""

    def test_instrumented_hot_loop_zero_readbacks(self, guard):
        from torchmetrics_tpu import observability as obs
        from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassF1Score

        preds, _, target = _cls_batch()
        with obs.telemetry_session() as rec:
            for m in (
                MulticlassAccuracy(5, average="micro", validate_args=False),
                MulticlassF1Score(5, average="macro", validate_args=False),
            ):
                m.update(preds, target)
                m.update(preds, target)
                m.forward(preds, target)
        snap = rec.counters.snapshot()
        assert snap["dispatches"] == 6
        assert snap["jit_compiles"] + snap["jit_cache_hits"] == snap["dispatches"]
        assert snap["d2h_readbacks"] == 0

    def test_blocking_timing_mode_no_readbacks(self, guard):
        # block_until_ready waits on futures without transferring — the honest
        # wall-clock mode must stay inside the no-D2H contract too
        from torchmetrics_tpu import observability as obs
        from torchmetrics_tpu.classification import MulticlassAccuracy

        preds, _, target = _cls_batch()
        with obs.telemetry_session(obs.TelemetryConfig(block_until_ready=True)) as rec:
            m = MulticlassAccuracy(5, average="micro", validate_args=False)
            m.update(preds, target)
            m.update(preds, target)
        assert rec.counters.snapshot()["d2h_readbacks"] == 0

    def test_disabled_telemetry_keeps_hot_loop_clean(self, guard):
        # the None-recorder branch is the production default: same zero-transfer
        # guarantee, no session anywhere in the process
        from torchmetrics_tpu import observability as obs
        from torchmetrics_tpu.classification import MulticlassAccuracy

        assert not obs.enabled()
        preds, _, target = _cls_batch()
        m = MulticlassAccuracy(5, average="micro", validate_args=False)
        m.update(preds, target)
        m.forward(preds, target)
