"""Regression tests for the round-4 ADVICE fixes."""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

import torchmetrics_tpu as tm


def test_dnsmos_mel_filterbank_matches_librosa_semantics_odd_nfft():
    """ADVICE r3: odd n_fft (DNSMOS uses 321) bin centers must be rfftfreq, not
    linspace(0, sr/2): the last rfft bin of an odd-length FFT sits BELOW Nyquist."""
    from torchmetrics_tpu.functional.audio.dnsmos import mel_filterbank

    sr, n_fft = 16000, 321
    freqs = np.fft.rfftfreq(n_fft, 1.0 / sr)
    assert freqs[-1] < sr / 2  # the property linspace gets wrong
    fb = mel_filterbank(sr, n_fft, 32)
    assert fb.shape == (32, 1 + n_fft // 2)
    # independent construction of the expected peak positions: each mel triangle
    # must peak at the rfft bin nearest its center frequency, which shifts by one
    # bin vs the linspace grid near Nyquist for odd n_fft
    from torchmetrics_tpu.functional.audio.dnsmos import _hz_to_mel_slaney, _mel_to_hz_slaney

    mel_pts = _mel_to_hz_slaney(np.linspace(_hz_to_mel_slaney(0.0), _hz_to_mel_slaney(sr / 2.0), 34))
    for m in range(0, 32, 8):
        peak_bin = int(np.argmax(fb[m]))
        expect = int(np.argmin(np.abs(freqs - mel_pts[m + 1])))
        assert abs(peak_bin - expect) <= 1, (m, peak_bin, expect)


def test_dnsmos_mel_filterbank_matches_librosa_if_present():
    """Self-activating cross-check wherever librosa exists (not in this pod)."""
    librosa = pytest.importorskip("librosa")
    from torchmetrics_tpu.functional.audio.dnsmos import mel_filterbank

    sr, n_fft = 16000, 321
    fb = mel_filterbank(sr, n_fft, 32)
    ref = librosa.filters.mel(sr=sr, n_fft=n_fft, n_mels=32, htk=False, norm="slaney")
    np.testing.assert_allclose(fb, ref, atol=1e-6)


def test_gather_unsupported_dtype_raises_after_shape_exchange():
    """ADVICE r3: an unsupported dtype is announced as a sentinel inside the shape
    collective (not raised before it), so peers can never be left blocked; every
    rank then raises together."""
    from torchmetrics_tpu.parallel.sync import gather_all_arrays

    with pytest.raises(ValueError, match="unsupported dtype"):
        gather_all_arrays(jnp.zeros(3, jnp.complex64))


def test_load_state_dict_default_state_keeps_zero_update_count():
    """ADVICE r3: loading a checkpoint saved BEFORE any update must not mark the
    metric as updated — compute() keeps warning instead of silently returning the
    zero-state value."""
    src = tm.classification.MulticlassAccuracy(3, average="micro")
    src.persistent(True)
    sd_fresh = src.state_dict()

    dst = tm.classification.MulticlassAccuracy(3, average="micro")
    dst.load_state_dict(sd_fresh)
    assert dst._update_count == 0

    # and a real checkpoint still counts as updated
    src.update(jnp.asarray(np.eye(3, dtype=np.float32)[[0, 1, 2]]), jnp.asarray([0, 1, 2]))
    sd_real = src.state_dict()
    dst2 = tm.classification.MulticlassAccuracy(3, average="micro")
    dst2.load_state_dict(sd_real)
    assert dst2._update_count >= 1
    assert float(dst2.compute()) == 1.0


def test_state_dict_roundtrip_preserves_update_count_even_at_default_values():
    """Code-review r4: SumMetric().update(0.0) leaves the state AT its default;
    the saved _update_count metadata must still mark the restore as updated."""
    src = tm.SumMetric()
    src.update(jnp.asarray(0.0))
    src.persistent(True)
    sd = src.state_dict()
    dst = tm.SumMetric()
    dst.load_state_dict(sd)
    assert dst._update_count >= 1
    assert float(dst.compute()) == 0.0


def test_merge_state_accepts_state_dict_with_metadata():
    """The _update_count metadata entry must not trip the unknown-state check and
    must weight mean states correctly."""
    a = tm.MeanMetric()
    a.update(jnp.asarray([1.0, 3.0]))
    b = tm.MeanMetric()
    b.update(jnp.asarray([5.0, 7.0]))
    b.persistent(True)
    a.merge_state(b.state_dict())
    assert float(a.compute()) == 4.0
