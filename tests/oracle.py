"""Parity oracle: import the *reference* torchmetrics (torch CPU) for golden values.

Usage in tests::

    from tests.oracle import reference_torchmetrics
    tm = reference_torchmetrics()           # None if unavailable -> skip
    ref = tm.functional.segmentation.dice_score(...)

The reference lives at /root/reference/src and needs a tiny ``lightning_utilities``
stub (tests/_oracle_stubs). Tests compare BEHAVIOR against it — the framework itself
never imports from the reference.
"""

from __future__ import annotations

import os
import sys

_REFERENCE_SRC = "/root/reference/src"
_STUBS = os.path.join(os.path.dirname(__file__), "_oracle_stubs")
_state = {"checked": False, "module": None}


def reference_torchmetrics():
    if _state["checked"]:
        return _state["module"]
    _state["checked"] = True
    if not os.path.isdir(_REFERENCE_SRC):
        return None
    for p in (_STUBS, _REFERENCE_SRC):
        if p not in sys.path:
            sys.path.insert(0, p)
    try:
        import torchmetrics  # noqa: F401

        _state["module"] = torchmetrics
    except Exception:
        _state["module"] = None
    return _state["module"]


def require_oracle():
    import pytest

    tm = reference_torchmetrics()
    if tm is None:
        pytest.skip("reference torchmetrics oracle unavailable")
    return tm
