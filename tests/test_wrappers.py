"""Wrapper-metric tests (reference tests/unittests/wrappers/)."""

import numpy as np
import jax.numpy as jnp
import pytest
from sklearn.metrics import accuracy_score, r2_score

from torchmetrics_tpu import MeanMetric
from torchmetrics_tpu.classification import BinaryAccuracy, MulticlassAccuracy, MulticlassPrecision
from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.regression import MeanSquaredError, R2Score
from torchmetrics_tpu.wrappers import (
    BinaryTargetTransformer,
    BootStrapper,
    ClasswiseWrapper,
    LambdaInputTransformer,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
    MultitaskWrapper,
    Running,
)

from conftest import seed_all

NUM_CLASSES = 5


class TestClasswiseWrapper:
    def test_output_keys_default_labels(self):
        rng = seed_all()
        metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=NUM_CLASSES, average=None))
        preds = jnp.asarray(rng.normal(size=(64, NUM_CLASSES)).astype(np.float32))
        target = jnp.asarray(rng.integers(0, NUM_CLASSES, 64))
        metric.update(preds, target)
        out = metric.compute()
        assert set(out.keys()) == {f"multiclassaccuracy_{i}" for i in range(NUM_CLASSES)}

    def test_custom_labels_and_values(self):
        rng = seed_all()
        labels = [f"c{i}" for i in range(NUM_CLASSES)]
        metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=NUM_CLASSES, average=None), labels=labels)
        raw = MulticlassAccuracy(num_classes=NUM_CLASSES, average=None)
        preds = jnp.asarray(rng.normal(size=(64, NUM_CLASSES)).astype(np.float32))
        target = jnp.asarray(rng.integers(0, NUM_CLASSES, 64))
        metric.update(preds, target)
        raw.update(preds, target)
        out = metric.compute()
        expected = raw.compute()
        for i, lab in enumerate(labels):
            np.testing.assert_allclose(out[f"multiclassaccuracy_{lab}"], expected[i], atol=1e-6)

    def test_forward_and_reset(self):
        rng = seed_all()
        metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=NUM_CLASSES, average=None), prefix="acc_")
        preds = jnp.asarray(rng.normal(size=(64, NUM_CLASSES)).astype(np.float32))
        target = jnp.asarray(rng.integers(0, NUM_CLASSES, 64))
        out = metric(preds, target)
        assert set(out.keys()) == {f"acc_{i}" for i in range(NUM_CLASSES)}
        metric.reset()
        assert metric.update_count == 0

    def test_raises_on_bad_args(self):
        with pytest.raises(ValueError):
            ClasswiseWrapper(1)
        with pytest.raises(ValueError):
            ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None), labels="notalist")

    def test_label_count_mismatch_raises(self):
        m = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None), labels=["a", "b", "c", "d"])
        m.update(jnp.asarray([[1.0, 0, 0], [0, 1.0, 0]]), jnp.asarray([0, 1]))
        with pytest.raises(ValueError, match="number of labels"):
            m.compute()


class TestBootStrapper:
    def test_mean_close_to_point_estimate(self):
        rng = seed_all()
        base = MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro")
        boot = BootStrapper(base, num_bootstraps=20, mean=True, std=True, raw=True, seed=1)
        point = base.clone()
        preds_all, target_all = [], []
        for _ in range(4):
            preds = jnp.asarray(rng.normal(size=(128, NUM_CLASSES)).astype(np.float32))
            target = jnp.asarray(rng.integers(0, NUM_CLASSES, 128))
            boot.update(preds, target)
            point.update(preds, target)
            preds_all.append(np.asarray(preds))
            target_all.append(np.asarray(target))
        out = boot.compute()
        assert out["raw"].shape[0] == 20
        ref = accuracy_score(np.concatenate(target_all), np.concatenate(preds_all).argmax(-1))
        # bootstrap mean should land within a few std of the point estimate
        assert abs(float(out["mean"]) - ref) < 5 * max(float(out["std"]), 1e-3)

    def test_quantile_output(self):
        rng = seed_all()
        boot = BootStrapper(
            MeanSquaredError(), num_bootstraps=8, quantile=[0.05, 0.95], raw=False, seed=2
        )
        preds = jnp.asarray(rng.normal(size=(256,)).astype(np.float32))
        target = jnp.asarray(rng.normal(size=(256,)).astype(np.float32))
        boot.update(preds, target)
        out = boot.compute()
        assert out["quantile"].shape == (2,)
        assert float(out["quantile"][0]) <= float(out["quantile"][1])

    def test_poisson_strategy(self):
        rng = seed_all()
        boot = BootStrapper(MeanSquaredError(jit=False), num_bootstraps=4, sampling_strategy="poisson", seed=3)
        preds = jnp.asarray(rng.normal(size=(64,)).astype(np.float32))
        target = jnp.asarray(rng.normal(size=(64,)).astype(np.float32))
        boot.update(preds, target)
        out = boot.compute()
        assert np.isfinite(float(out["mean"]))

    def test_raises(self):
        with pytest.raises(ValueError):
            BootStrapper(MeanSquaredError(), sampling_strategy="bogus")
        with pytest.raises(ValueError):
            BootStrapper(17)

    def test_forward_is_batch_only(self):
        rng = seed_all()
        boot = BootStrapper(MeanSquaredError(), num_bootstraps=4, seed=5)
        p1, t1 = jnp.zeros(32), jnp.zeros(32)  # perfect batch: mse 0
        p2 = jnp.asarray(rng.normal(size=32).astype(np.float32)) + 10.0
        t2 = jnp.zeros(32)
        boot(p1, t1)
        out2 = boot(p2, t2)
        # second forward value covers batch 2 alone (mse ~100), not the running mix (~50)
        assert float(out2["mean"]) > 60.0
        # while global state covers both batches
        assert float(boot.compute()["mean"]) < 60.0


class TestMinMaxMetric:
    def test_tracks_extremes(self):
        acc = MinMaxMetric(BinaryAccuracy())
        # first batch: 100% accuracy
        out1 = acc(jnp.asarray([1.0, 1.0, 0.0]), jnp.asarray([1, 1, 0]))
        assert float(out1["raw"]) == 1.0
        assert float(out1["max"]) == 1.0
        # second batch: accuracy falls; max stays, min follows the cumulative value
        acc.update(jnp.asarray([0.0, 0.0, 0.0]), jnp.asarray([1, 1, 1]))
        out2 = acc.compute()
        assert float(out2["raw"]) == 0.5
        assert float(out2["max"]) == 1.0
        assert float(out2["min"]) == 0.5
        acc.reset()
        assert float(acc.min_val) == np.inf

    def test_raises_on_nonscalar(self):
        mm = MinMaxMetric(MulticlassAccuracy(num_classes=3, average=None))
        mm.update(jnp.asarray([[1.0, 0, 0], [0, 1.0, 0]]), jnp.asarray([0, 1]))
        with pytest.raises(RuntimeError):
            mm.compute()


class TestMultioutputWrapper:
    def test_r2_multioutput_vs_sklearn(self):
        rng = seed_all()
        metric = MultioutputWrapper(R2Score(), num_outputs=2)
        preds = rng.normal(size=(4, 64, 2)).astype(np.float32)
        target = (preds + 0.3 * rng.normal(size=(4, 64, 2))).astype(np.float32)
        for i in range(4):
            metric.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
        out = np.asarray(metric.compute())
        p, t = preds.reshape(-1, 2), target.reshape(-1, 2)
        ref = [r2_score(t[:, j], p[:, j]) for j in range(2)]
        np.testing.assert_allclose(out, ref, atol=1e-4)

    def test_remove_nans(self):
        metric = MultioutputWrapper(MeanSquaredError(jit=False), num_outputs=2, remove_nans=True)
        preds = jnp.asarray([[1.0, 2.0], [np.nan, 3.0], [2.0, np.nan]])
        target = jnp.asarray([[1.0, 2.0], [1.0, 3.0], [2.0, 1.0]])
        metric.update(preds, target)
        out = np.asarray(metric.compute())
        np.testing.assert_allclose(out, [0.0, (3.0 - 3.0) ** 2 / 2 + (2.0 - 2.0) ** 2 / 2], atol=1e-6)

    def test_forward_stacks(self):
        rng = seed_all()
        metric = MultioutputWrapper(MeanSquaredError(), num_outputs=3)
        preds = jnp.asarray(rng.normal(size=(32, 3)).astype(np.float32))
        out = metric(preds, preds)
        assert out.shape == (3,)
        np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


class TestMultitaskWrapper:
    def test_mixed_tasks(self):
        rng = seed_all()
        wrapper = MultitaskWrapper(
            {
                "cls": BinaryAccuracy(),
                "reg": MeanSquaredError(),
            }
        )
        preds_c = jnp.asarray((rng.random(64) > 0.5).astype(np.float32))
        target_c = jnp.asarray(rng.integers(0, 2, 64))
        preds_r = jnp.asarray(rng.normal(size=64).astype(np.float32))
        target_r = jnp.asarray(rng.normal(size=64).astype(np.float32))
        wrapper.update({"cls": preds_c, "reg": preds_r}, {"cls": target_c, "reg": target_r})
        out = wrapper.compute()
        ref_acc = accuracy_score(np.asarray(target_c), np.asarray(preds_c) > 0.5)
        ref_mse = np.mean((np.asarray(preds_r) - np.asarray(target_r)) ** 2)
        np.testing.assert_allclose(float(out["cls"]), ref_acc, atol=1e-6)
        np.testing.assert_allclose(float(out["reg"]), ref_mse, atol=1e-5)

    def test_prefix_postfix_and_key_mismatch(self):
        wrapper = MultitaskWrapper({"a": MeanSquaredError()}, prefix="p_", postfix="_s")
        x = jnp.ones(4)
        wrapper.update({"a": x}, {"a": x})
        assert list(wrapper.compute().keys()) == ["p_a_s"]
        with pytest.raises(ValueError):
            wrapper.update({"b": x}, {"a": x})

    def test_nested_collection(self):
        rng = seed_all()
        wrapper = MultitaskWrapper({"cls": MetricCollection([BinaryAccuracy()])})
        preds = jnp.asarray((rng.random(32)).astype(np.float32))
        target = jnp.asarray(rng.integers(0, 2, 32))
        wrapper.update({"cls": preds}, {"cls": target})
        out = wrapper.compute()
        assert "BinaryAccuracy" in out["cls"]


class TestRunning:
    def test_window_mean(self):
        metric = Running(MeanMetric(), window=3)
        vals = [1.0, 2.0, 3.0, 4.0, 5.0]
        for v in vals:
            metric.update(jnp.asarray(v))
        # only the last 3 count
        np.testing.assert_allclose(float(metric.compute()), np.mean(vals[-3:]), atol=1e-6)

    def test_window_accuracy_statefulness(self):
        rng = seed_all()
        base = BinaryAccuracy()
        metric = Running(base, window=2)
        chunks = []
        for _ in range(4):
            p = jnp.asarray(rng.random(16).astype(np.float32))
            t = jnp.asarray(rng.integers(0, 2, 16))
            metric.update(p, t)
            chunks.append((np.asarray(p), np.asarray(t)))
        p = np.concatenate([c[0] for c in chunks[-2:]])
        t = np.concatenate([c[1] for c in chunks[-2:]])
        np.testing.assert_allclose(float(metric.compute()), accuracy_score(t, p > 0.5), atol=1e-6)
        # base metric state is untouched by the windowed bookkeeping
        assert base.update_count == 0

    def test_forward_returns_batch_value(self):
        metric = Running(MeanMetric(), window=2)
        v = metric(jnp.asarray([2.0, 4.0]))
        np.testing.assert_allclose(float(v), 3.0, atol=1e-6)

    def test_raises(self):
        with pytest.raises(ValueError):
            Running(MeanMetric(), window=0)
        with pytest.raises(ValueError):
            Running(7)


class TestMetricTracker:
    def test_best_metric_single(self):
        rng = seed_all()
        tracker = MetricTracker(MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro"), maximize=True)
        accs = []
        for step in range(3):
            tracker.increment()
            preds = jnp.asarray(rng.normal(size=(64, NUM_CLASSES)).astype(np.float32))
            target = jnp.asarray(rng.integers(0, NUM_CLASSES, 64))
            tracker.update(preds, target)
            accs.append(float(tracker.compute()))
        all_vals = np.asarray(tracker.compute_all())
        np.testing.assert_allclose(all_vals, accs, atol=1e-6)
        best, step = tracker.best_metric(return_step=True)
        assert best == max(accs)
        assert step == int(np.argmax(accs))
        assert tracker.n_steps == 3

    def test_collection_tracking(self):
        rng = seed_all()
        coll = MetricCollection([MulticlassAccuracy(NUM_CLASSES), MulticlassPrecision(NUM_CLASSES)])
        tracker = MetricTracker(coll, maximize=[True, True])
        for _ in range(2):
            tracker.increment()
            preds = jnp.asarray(rng.normal(size=(64, NUM_CLASSES)).astype(np.float32))
            target = jnp.asarray(rng.integers(0, NUM_CLASSES, 64))
            tracker.update(preds, target)
        res = tracker.compute_all()
        assert set(res.keys()) == {"MulticlassAccuracy", "MulticlassPrecision"}
        assert res["MulticlassAccuracy"].shape == (2,)
        best = tracker.best_metric()
        assert set(best.keys()) == {"MulticlassAccuracy", "MulticlassPrecision"}

    def test_raises_before_increment(self):
        tracker = MetricTracker(MeanSquaredError(), maximize=False)
        with pytest.raises(ValueError):
            tracker.update(jnp.ones(2), jnp.ones(2))
        with pytest.raises(ValueError):
            tracker.compute()

    def test_maximize_inference(self):
        # BinaryAccuracy declares higher_is_better=True
        tracker = MetricTracker(BinaryAccuracy())
        assert tracker.maximize is True


class TestTransformations:
    def test_lambda_transform(self):
        metric = LambdaInputTransformer(
            BinaryAccuracy(),
            transform_pred=lambda p: 1.0 - p,
        )
        preds = jnp.asarray([0.9, 0.1, 0.8, 0.3])
        target = jnp.asarray([0, 1, 0, 1])
        metric.update(preds, target)
        np.testing.assert_allclose(float(metric.compute()), 1.0, atol=1e-6)

    def test_binary_target_transformer(self):
        metric = BinaryTargetTransformer(BinaryAccuracy(), threshold=2.0)
        preds = jnp.asarray([0.9, 0.1, 0.9, 0.2])
        target = jnp.asarray([5.0, 0.5, 3.0, 1.0])  # binarizes to [1, 0, 1, 0]
        metric.update(preds, target)
        np.testing.assert_allclose(float(metric.compute()), 1.0, atol=1e-6)

    def test_raises(self):
        with pytest.raises(TypeError):
            LambdaInputTransformer(BinaryAccuracy(), transform_pred=123)
        with pytest.raises(TypeError):
            BinaryTargetTransformer(BinaryAccuracy(), threshold="x")
        with pytest.raises(TypeError):
            BinaryTargetTransformer(42)
