"""L6 integration: a toy pjit train/eval loop with metrics inside ``shard_map`` on the
8-device CPU mesh (SURVEY §7 step 2's "one model running" milestone; the reference's
analogue is ``tests/integrations/test_lightning.py``). Doubles as executable
documentation for the recommended training-loop wiring.
"""

from __future__ import annotations

import numpy as np
import jax
from torchmetrics_tpu.parallel import shard_map as _shard_map
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from tests.helpers import _assert_allclose

from torchmetrics_tpu import MeanMetric, MetricCollection
from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassF1Score


def _make_data(rng, n=512, d=16, num_classes=4):
    w_true = rng.normal(size=(d, num_classes)).astype(np.float32)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ w_true + 0.1 * rng.normal(size=(n, num_classes))).argmax(-1).astype(np.int32)
    return x, y


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device CPU mesh")
def test_pjit_train_eval_loop_with_metrics():
    num_classes, d = 4, 16
    rng = np.random.default_rng(0)
    x, y = _make_data(rng, n=512, d=d, num_classes=num_classes)

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("data",))
    data_sharding = NamedSharding(mesh, P("data"))
    replicated = NamedSharding(mesh, P())

    params = {
        "w": jnp.zeros((d, num_classes)),
        "b": jnp.zeros((num_classes,)),
    }
    params = jax.device_put(params, replicated)
    opt = optax.sgd(0.5)
    opt_state = opt.init(params)

    collection = MetricCollection({
        "acc": MulticlassAccuracy(num_classes, average="micro", validate_args=False),
        "f1": MulticlassF1Score(num_classes, average="macro", validate_args=False),
    })
    pure = collection.as_pure()
    loss_metric = MeanMetric()

    def loss_fn(params, xb, yb):
        logits = xb @ params["w"] + params["b"]
        return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

    @jax.jit
    def train_step(params, opt_state, xb, yb):
        # data arrives sharded over the mesh; jit + shardings insert the collectives
        loss, grads = jax.value_and_grad(loss_fn)(params, xb, yb)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    def eval_shard(params, xb, yb):
        # per-shard metric state + in-graph psum — the in-graph sync plane
        logits = xb @ params["w"] + params["b"]
        local = pure.update(pure.init(), jax.nn.softmax(logits), yb)
        return pure.reduce(local, "data")

    eval_step = jax.jit(
        _shard_map(eval_shard, mesh=mesh, in_specs=(P(), P("data"), P("data")), out_specs=P())
    )

    batch = 128
    for epoch in range(30):
        for start in range(0, len(x), batch):
            xb = jax.device_put(jnp.asarray(x[start : start + batch]), data_sharding)
            yb = jax.device_put(jnp.asarray(y[start : start + batch]), data_sharding)
            params, opt_state, loss = train_step(params, opt_state, xb, yb)
            loss_metric.update(loss)

    # eval epoch: accumulate synced per-batch states into the stateful collection
    final_states = pure.init()
    merge = jax.jit(lambda a, b: jax.tree.map(jnp.add, a, b))  # all-sum states here
    for start in range(0, len(x), batch):
        xb = jax.device_put(jnp.asarray(x[start : start + batch]), data_sharding)
        yb = jax.device_put(jnp.asarray(y[start : start + batch]), data_sharding)
        final_states = merge(final_states, eval_step(params, xb, yb))
    values = pure.compute(final_states)

    # the model must actually have learned, and the sharded metrics must agree with a
    # single-device recomputation over the full dataset
    assert float(values["acc"]) > 0.9
    single = MetricCollection({
        "acc": MulticlassAccuracy(num_classes, average="micro", validate_args=False),
        "f1": MulticlassF1Score(num_classes, average="macro", validate_args=False),
    })
    logits = jnp.asarray(x) @ params["w"] + params["b"]
    single.update(jax.nn.softmax(logits), jnp.asarray(y))
    _assert_allclose(values, single.compute(), atol=1e-5)
    assert float(loss_metric.compute()) > 0.0
