"""BERTScore parity against the reference through a REAL local HF pipeline.

Round 2 verified BERTScore only through toy embedder seams; this drives both
implementations through their standard ``AutoModel``/``AutoTokenizer`` loaders
on a tiny randomly-initialized BERT saved to disk — full tokenizer + hidden-state
+ idf + greedy-matching parity without any downloads.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from tests.oracle import reference_torchmetrics

transformers = pytest.importorskip("transformers")

PREDS = [
    "the cat sat on the mat",
    "a quick brown fox jumps over a lazy dog",
    "deep nets learn representations",
]
TARGETS = [
    "the cat lay on the rug",
    "the quick brown fox jumped over the lazy dog",
    "neural networks learn features",
]

VOCAB = (
    "[PAD] [UNK] [CLS] [SEP] [MASK] the a cat sat lay on mat rug quick brown fox jumps "
    "jumped over lazy dog deep neural nets networks learn representations features".split()
)


@pytest.fixture(scope="module")
def tiny_bert_dir(tmp_path_factory):
    import torch
    from transformers import BertConfig, BertModel, BertTokenizer

    d = tmp_path_factory.mktemp("tiny_bert")
    with open(os.path.join(d, "vocab.txt"), "w") as f:
        f.write("\n".join(VOCAB))
    tokenizer = BertTokenizer(os.path.join(d, "vocab.txt"))
    torch.manual_seed(1)
    config = BertConfig(
        vocab_size=len(VOCAB), hidden_size=32, num_hidden_layers=3, num_attention_heads=2,
        intermediate_size=64, max_position_embeddings=64,
    )
    BertModel(config).save_pretrained(d)
    tokenizer.save_pretrained(d)
    return str(d)


def _length_perm(model_dir):
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(model_dir, local_files_only=True)
    lengths = np.asarray(tok(PREDS, padding=True, return_tensors="np")["attention_mask"].sum(1))
    return np.argsort(lengths, kind="stable")


@pytest.mark.parametrize("idf", [False, True])
@pytest.mark.parametrize("num_layers", [None, 2])
def test_bert_score_vs_reference_real_hf(tiny_bert_dir, idf, num_layers):
    tm = reference_torchmetrics()
    if tm is None:
        pytest.skip("reference torchmetrics unavailable")
    from torchmetrics.functional.text.bert import bert_score as ref_bert_score

    from torchmetrics_tpu.functional.text import bert_score

    ref = ref_bert_score(
        PREDS, TARGETS, model_name_or_path=tiny_bert_dir, idf=idf, num_layers=num_layers,
        verbose=False,
    )
    ours = bert_score(PREDS, TARGETS, model_name_or_path=tiny_bert_dir, idf=idf, num_layers=num_layers)
    # The reference mis-unsorts its length-sorted batches (applies the sorting
    # permutation twice, bert.py:563-567): ref[i] == ours[s[s[i]]] with s the length
    # argsort (PREDS/TARGETS share an ordering here so its pairing stays aligned)
    s = _length_perm(tiny_bert_dir)
    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(
            np.asarray(ours[key])[s][s], np.asarray(ref[key]), atol=5e-5, err_msg=key
        )


def test_bert_score_class_vs_reference_real_hf(tiny_bert_dir):
    tm = reference_torchmetrics()
    if tm is None:
        pytest.skip("reference torchmetrics unavailable")
    from torchmetrics.text.bert import BERTScore as RefBERTScore

    from torchmetrics_tpu.text import BERTScore

    # max_length=32: the class pads state rows to max_length (static concat width)
    # and the tiny model only has 64 position embeddings
    ref = RefBERTScore(model_name_or_path=tiny_bert_dir, idf=True, verbose=False, max_length=32, truncation=True)
    ours = BERTScore(model_name_or_path=tiny_bert_dir, idf=True, max_length=32, truncation=True)
    for i in range(0, len(PREDS), 2):
        ref.update(PREDS[i : i + 2], TARGETS[i : i + 2])
        ours.update(PREDS[i : i + 2], TARGETS[i : i + 2])
    ref_out = ref.compute()
    ours_out = ours.compute()
    s = _length_perm(tiny_bert_dir)
    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(
            np.asarray(ours_out[key])[s][s], np.asarray(ref_out[key]), atol=5e-5, err_msg=key
        )
