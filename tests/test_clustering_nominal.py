"""Clustering + nominal + shape + pairwise parity tests (sklearn/scipy golden
references, reference-torchmetrics oracle where sklearn has no equivalent)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from tests.helpers import _assert_allclose
from tests.oracle import reference_torchmetrics

import torchmetrics_tpu as tm
import torchmetrics_tpu.functional as F

_RNG = np.random.default_rng(42)
NUM_BATCHES, BATCH = 4, 48
LABELS_P = _RNG.integers(0, 5, (NUM_BATCHES, BATCH))
LABELS_T = _RNG.integers(0, 5, (NUM_BATCHES, BATCH))
DATA = _RNG.normal(size=(NUM_BATCHES, BATCH, 3)).astype(np.float32)


# ------------------------------------------------------------------- pairwise

@pytest.mark.parametrize("reduction", [None, "mean", "sum"])
@pytest.mark.parametrize(
    "fn,ref",
    [
        (F.pairwise_cosine_similarity, "cosine"),
        (F.pairwise_euclidean_distance, "euclidean"),
        (F.pairwise_linear_similarity, "linear"),
        (F.pairwise_manhattan_distance, "manhattan"),
        (F.pairwise_minkowski_distance, "minkowski"),
    ],
)
def test_pairwise_vs_sklearn(fn, ref, reduction):
    from sklearn.metrics.pairwise import (
        cosine_similarity,
        euclidean_distances,
        linear_kernel,
        manhattan_distances,
    )
    from scipy.spatial.distance import cdist

    x = _RNG.normal(size=(6, 4)).astype(np.float32)
    y = _RNG.normal(size=(5, 4)).astype(np.float32)
    ref_fn = {
        "cosine": cosine_similarity,
        "euclidean": euclidean_distances,
        "linear": linear_kernel,
        "manhattan": manhattan_distances,
        "minkowski": lambda a, b: cdist(a, b, metric="minkowski", p=3),
    }[ref]
    kwargs = {"exponent": 3} if ref == "minkowski" else {}
    expected = ref_fn(x, y)
    if reduction == "mean":
        expected = expected.mean(-1)
    elif reduction == "sum":
        expected = expected.sum(-1)
    _assert_allclose(fn(jnp.asarray(x), jnp.asarray(y), reduction=reduction, **kwargs), expected, atol=1e-4)
    # self-comparison path zeroes the diagonal
    self_mat = np.asarray(fn(jnp.asarray(x), **kwargs))
    assert np.allclose(np.diagonal(self_mat), 0)


def test_pairwise_validation():
    with pytest.raises(ValueError, match="Expected argument `x`"):
        F.pairwise_cosine_similarity(jnp.zeros((3,)))
    with pytest.raises(ValueError, match="Expected argument `y`"):
        F.pairwise_cosine_similarity(jnp.zeros((3, 2)), jnp.zeros((3, 4)))
    with pytest.raises(ValueError, match="Expected reduction"):
        F.pairwise_cosine_similarity(jnp.zeros((3, 2)), reduction="bad")


# ------------------------------------------------------------------ clustering

EXTRINSIC = [
    (tm.MutualInfoScore, F.mutual_info_score, "mutual_info_score", {}),
    (tm.AdjustedMutualInfoScore, F.adjusted_mutual_info_score, "adjusted_mutual_info_score", {}),
    (tm.NormalizedMutualInfoScore, F.normalized_mutual_info_score, "normalized_mutual_info_score", {}),
    (tm.RandScore, F.rand_score, "rand_score", {}),
    (tm.AdjustedRandScore, F.adjusted_rand_score, "adjusted_rand_score", {}),
    (tm.FowlkesMallowsIndex, F.fowlkes_mallows_index, "fowlkes_mallows_score", {}),
    (tm.HomogeneityScore, F.homogeneity_score, "homogeneity_score", {}),
    (tm.CompletenessScore, F.completeness_score, "completeness_score", {}),
    (tm.VMeasureScore, F.v_measure_score, "v_measure_score", {}),
]


@pytest.mark.parametrize("cls,fn,sk_name,kwargs", EXTRINSIC, ids=[e[2] for e in EXTRINSIC])
def test_extrinsic_clustering_vs_sklearn(cls, fn, sk_name, kwargs):
    import sklearn.metrics as skm

    sk_fn = getattr(skm, sk_name, None) or getattr(skm.cluster, sk_name)
    # functional per batch
    for i in range(NUM_BATCHES):
        ours = fn(jnp.asarray(LABELS_P[i]), jnp.asarray(LABELS_T[i]), **kwargs)
        ref = sk_fn(LABELS_T[i], LABELS_P[i])
        _assert_allclose(ours, ref, atol=1e-5, msg=f"batch {i}")
    # stateful accumulation over all batches
    m = cls(**kwargs)
    for i in range(NUM_BATCHES):
        m.update(jnp.asarray(LABELS_P[i]), jnp.asarray(LABELS_T[i]))
    _assert_allclose(m.compute(), sk_fn(LABELS_T.reshape(-1), LABELS_P.reshape(-1)), atol=1e-5)


@pytest.mark.parametrize(
    "cls,fn,sk_name",
    [
        (tm.CalinskiHarabaszScore, F.calinski_harabasz_score, "calinski_harabasz_score"),
        (tm.DaviesBouldinScore, F.davies_bouldin_score, "davies_bouldin_score"),
    ],
)
def test_intrinsic_clustering_vs_sklearn(cls, fn, sk_name):
    import sklearn.metrics as skm

    sk_fn = getattr(skm, sk_name)
    for i in range(NUM_BATCHES):
        ours = fn(jnp.asarray(DATA[i]), jnp.asarray(LABELS_T[i]))
        _assert_allclose(ours, sk_fn(DATA[i], LABELS_T[i]), atol=1e-4, msg=f"batch {i}")
    m = cls()
    for i in range(NUM_BATCHES):
        m.update(jnp.asarray(DATA[i]), jnp.asarray(LABELS_T[i]))
    _assert_allclose(m.compute(), sk_fn(DATA.reshape(-1, 3), LABELS_T.reshape(-1)), atol=1e-4)


def test_dunn_index_vs_oracle():
    tm_ref = reference_torchmetrics()
    if tm_ref is None:
        pytest.skip("oracle unavailable")
    import torch

    from torchmetrics.functional.clustering import dunn_index as ref_dunn  # type: ignore

    for p in (2, 3):
        ours = F.dunn_index(jnp.asarray(DATA[0]), jnp.asarray(LABELS_T[0]), p=p)
        ref = ref_dunn(torch.as_tensor(DATA[0]), torch.as_tensor(LABELS_T[0]), p=p)
        _assert_allclose(ours, ref.numpy(), atol=1e-4)
    m = tm.DunnIndex(p=2)
    m.update(jnp.asarray(DATA[0]), jnp.asarray(LABELS_T[0]))
    _assert_allclose(m.compute(), F.dunn_index(jnp.asarray(DATA[0]), jnp.asarray(LABELS_T[0])), atol=1e-6)


def test_cluster_accuracy():
    # permuted labels are a perfect clustering under optimal assignment
    perm = np.array([2, 0, 3, 4, 1])
    preds = perm[LABELS_T[0]]
    m = tm.ClusterAccuracy(num_classes=5)
    m.update(jnp.asarray(preds), jnp.asarray(LABELS_T[0]))
    assert float(m.compute()) == pytest.approx(1.0)
    val = F.cluster_accuracy(jnp.asarray(LABELS_P[0]), jnp.asarray(LABELS_T[0]), num_classes=5)
    assert 0.0 <= float(val) <= 1.0


def test_clustering_merge_matches_single():
    single = tm.MutualInfoScore()
    shards = [tm.MutualInfoScore() for _ in range(3)]
    for i in range(3):
        single.update(jnp.asarray(LABELS_P[i]), jnp.asarray(LABELS_T[i]))
        shards[i].update(jnp.asarray(LABELS_P[i]), jnp.asarray(LABELS_T[i]))
    merged = shards[0]
    merged.merge_state(shards[1])
    merged.merge_state(shards[2])
    _assert_allclose(merged.compute(), single.compute(), atol=1e-6)


# -------------------------------------------------------------------- nominal

NOMINAL = [
    (tm.CramersV, F.cramers_v, "CramersV", "cramers_v", {"bias_correction": True}),
    (tm.CramersV, F.cramers_v, "CramersV", "cramers_v", {"bias_correction": False}),
    (tm.PearsonsContingencyCoefficient, F.pearsons_contingency_coefficient,
     "PearsonsContingencyCoefficient", "pearsons_contingency_coefficient", {}),
    (tm.TheilsU, F.theils_u, "TheilsU", "theils_u", {}),
    (tm.TschuprowsT, F.tschuprows_t, "TschuprowsT", "tschuprows_t", {"bias_correction": True}),
    (tm.TschuprowsT, F.tschuprows_t, "TschuprowsT", "tschuprows_t", {"bias_correction": False}),
]


@pytest.mark.parametrize("cls,fn,ref_cls_name,ref_fn_name,kwargs", NOMINAL,
                         ids=[f"{n[3]}-{n[4]}" for n in NOMINAL])
def test_nominal_vs_oracle(cls, fn, ref_cls_name, ref_fn_name, kwargs):
    tm_ref = reference_torchmetrics()
    if tm_ref is None:
        pytest.skip("oracle unavailable")
    import torch

    import torchmetrics.functional.nominal as ref_nominal  # type: ignore

    ref_fn = getattr(ref_nominal, ref_fn_name)
    for i in range(NUM_BATCHES):
        ours = fn(jnp.asarray(LABELS_P[i]), jnp.asarray(LABELS_T[i]), **kwargs)
        ref = ref_fn(torch.as_tensor(LABELS_P[i]), torch.as_tensor(LABELS_T[i]), **kwargs)
        _assert_allclose(ours, ref.numpy(), atol=1e-5, msg=f"batch {i}")
    import torchmetrics.nominal as ref_nominal_cls  # type: ignore

    ours_m = cls(num_classes=5, **kwargs)
    ref_m = getattr(ref_nominal_cls, ref_cls_name)(num_classes=5, **kwargs)
    for i in range(NUM_BATCHES):
        ours_m.update(jnp.asarray(LABELS_P[i]), jnp.asarray(LABELS_T[i]))
        ref_m.update(torch.as_tensor(LABELS_P[i]), torch.as_tensor(LABELS_T[i]))
    _assert_allclose(ours_m.compute(), ref_m.compute().numpy(), atol=1e-5)


def test_fleiss_kappa_vs_oracle():
    tm_ref = reference_torchmetrics()
    if tm_ref is None:
        pytest.skip("oracle unavailable")
    import torch

    counts = _RNG.integers(0, 10, (40, 5))
    ours = F.fleiss_kappa(jnp.asarray(counts))
    from torchmetrics.functional.nominal import fleiss_kappa as ref_fleiss  # type: ignore

    ref = ref_fleiss(torch.as_tensor(counts).long())
    _assert_allclose(ours, ref.numpy(), atol=1e-5)
    m = tm.FleissKappa(mode="counts")
    m.update(jnp.asarray(counts[:20]))
    m.update(jnp.asarray(counts[20:]))
    _assert_allclose(m.compute(), ref.numpy(), atol=1e-5)
    # probs mode smoke (C == R so the reference's internal reshape quirk is inert)
    probs = _RNG.normal(size=(30, 5, 5)).astype(np.float32)
    ours_p = F.fleiss_kappa(jnp.asarray(probs), mode="probs")
    ref_p = ref_fleiss(torch.as_tensor(probs), mode="probs")
    _assert_allclose(ours_p, ref_p.numpy(), atol=1e-5)


def test_nominal_nan_strategies():
    preds = np.array([0.0, 1.0, np.nan, 2.0, 1.0, 0.0])
    target = np.array([0.0, 1.0, 2.0, np.nan, 1.0, 0.0])
    for strategy, repl in (("replace", 0.0), ("drop", None)):
        val = F.cramers_v(preds, target, nan_strategy=strategy, nan_replace_value=repl or 0.0)
        assert np.isfinite(float(val))
    with pytest.raises(ValueError, match="nan_strategy"):
        tm.CramersV(num_classes=3, nan_strategy="bad")


# ----------------------------------------------------------------------- shape

def test_procrustes_vs_scipy():
    from scipy.spatial import procrustes as scipy_procrustes

    a = _RNG.normal(size=(4, 10, 3)).astype(np.float32)
    b = _RNG.normal(size=(4, 10, 3)).astype(np.float32)
    ours = np.asarray(F.procrustes_disparity(jnp.asarray(a), jnp.asarray(b)))
    for i in range(4):
        _, _, disparity = scipy_procrustes(a[i], b[i])
        assert np.isclose(ours[i], disparity, atol=1e-4)
    m = tm.ProcrustesDisparity(reduction="mean")
    m.update(jnp.asarray(a), jnp.asarray(b))
    _assert_allclose(m.compute(), ours.mean(), atol=1e-5)
    m2 = tm.ProcrustesDisparity(reduction="sum")
    m2.update(jnp.asarray(a[:2]), jnp.asarray(b[:2]))
    m2.update(jnp.asarray(a[2:]), jnp.asarray(b[2:]))
    _assert_allclose(m2.compute(), ours.sum(), atol=1e-5)


def test_procrustes_validation():
    with pytest.raises(ValueError, match="3D tensors"):
        F.procrustes_disparity(jnp.zeros((3, 2)), jnp.zeros((3, 2)))
    with pytest.raises(ValueError, match="reduction"):
        tm.ProcrustesDisparity(reduction="bad")


def test_nominal_2d_probability_inputs():
    """Regression: num_classes must be inferred after the argmax collapse."""
    tm_ref = reference_torchmetrics()
    if tm_ref is None:
        pytest.skip("oracle unavailable")
    import torch
    import torchmetrics.functional.nominal as ref_nominal  # type: ignore

    probs_p = _RNG.dirichlet(np.ones(5), size=64).astype(np.float32)
    probs_t = _RNG.dirichlet(np.ones(5), size=64).astype(np.float32)
    for fn, ref_name in ((F.cramers_v, "cramers_v"), (F.theils_u, "theils_u")):
        ours = fn(jnp.asarray(probs_p), jnp.asarray(probs_t))
        ref = getattr(ref_nominal, ref_name)(torch.as_tensor(probs_p), torch.as_tensor(probs_t))
        _assert_allclose(ours, ref.numpy(), atol=1e-5)


def test_cluster_accuracy_rejects_out_of_range():
    m = tm.ClusterAccuracy(num_classes=3)
    with pytest.raises(ValueError, match="labels in"):
        m.update(jnp.asarray(np.array([0, 1, 2, 7, 7, 7])), jnp.asarray(np.array([0, 1, 2, 0, 1, 2])))


def test_yates_correction_scipy_semantics():
    """Regression: Yates correction clamps by |observed-expected|, not blindly 0.5."""
    from scipy.stats import chi2_contingency

    preds = np.array([1] + [0] + [1] * 18 + [1])
    target = np.array([0] + [1] + [1] * 18 + [1])
    # build the 2x2 table scipy sees
    table = np.zeros((2, 2))
    np.add.at(table, (target, preds), 1)
    chi2 = chi2_contingency(table, correction=True).statistic
    ours = float(F.cramers_v(preds, target, bias_correction=True))
    # direct check on the chi-squared kernel
    from torchmetrics_tpu.functional.nominal.utils import _compute_chi_squared

    assert np.isclose(_compute_chi_squared(table.astype(float), bias_correction=True), chi2, atol=1e-8)
    assert np.isfinite(ours)


def test_dunn_index_validation():
    with pytest.raises(ValueError, match="Number of detected clusters"):
        F.dunn_index(jnp.asarray(DATA[0]), jnp.zeros(DATA[0].shape[0], jnp.int32))
    with pytest.raises(ValueError, match="Expected 2D data"):
        F.dunn_index(jnp.zeros((8,)), jnp.zeros(8, jnp.int32))
