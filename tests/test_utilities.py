"""Utility kernel tests (reference tests/unittests/utilities/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.utilities.compute import _auc_compute, _safe_divide, _safe_xlogy, normalize_logits_if_needed
from torchmetrics_tpu.utilities.data import (
    _bincount,
    _bincount_2d,
    _bincount_matmul,
    dim_zero_cat,
    select_topk,
    to_categorical,
    to_onehot,
)

from conftest import seed_all


def test_safe_divide():
    num = jnp.asarray([1.0, 2.0, 3.0])
    denom = jnp.asarray([2.0, 0.0, 6.0])
    out = _safe_divide(num, denom)
    np.testing.assert_allclose(np.asarray(out), [0.5, 0.0, 0.5])
    out1 = _safe_divide(num, denom, zero_division=1.0)
    np.testing.assert_allclose(np.asarray(out1), [0.5, 1.0, 0.5])


def test_safe_divide_jit():
    out = jax.jit(_safe_divide)(jnp.asarray([4.0]), jnp.asarray([0.0]))
    np.testing.assert_allclose(np.asarray(out), [0.0])


def test_safe_xlogy():
    x = jnp.asarray([0.0, 1.0, 2.0])
    y = jnp.asarray([0.0, jnp.e, jnp.e])
    out = _safe_xlogy(x, y)
    np.testing.assert_allclose(np.asarray(out), [0.0, 1.0, 2.0], atol=1e-6)


@pytest.mark.parametrize("fn", [_bincount, _bincount_matmul])
def test_bincount_matches_numpy(fn):
    rng = seed_all(0)
    x = rng.integers(0, 10, size=1000)
    ours = np.asarray(fn(jnp.asarray(x), minlength=10))
    ref = np.bincount(x, minlength=10)
    np.testing.assert_array_equal(ours, ref)


def test_bincount_out_of_range_dropped():
    x = jnp.asarray([0, 1, -1, 5, 2])
    out = np.asarray(_bincount(x, minlength=3))
    np.testing.assert_array_equal(out, [1, 1, 1])


def test_bincount_2d_confusion():
    t = jnp.asarray([0, 0, 1, 2, 2, 2])
    p = jnp.asarray([0, 1, 1, 2, 0, 2])
    cm = np.asarray(_bincount_2d(t, p, 3, 3))
    expected = np.asarray([[1, 1, 0], [0, 1, 0], [1, 0, 2]])
    np.testing.assert_array_equal(cm, expected)


def test_to_onehot_roundtrip():
    labels = jnp.asarray([0, 2, 1, 3])
    oh = to_onehot(labels, 4)
    assert oh.shape == (4, 4)
    np.testing.assert_array_equal(np.asarray(to_categorical(oh)), np.asarray(labels))


@pytest.mark.parametrize("topk", [1, 2, 3])
def test_select_topk(topk):
    rng = seed_all(1)
    probs = rng.random((8, 5)).astype(np.float32)
    mask = np.asarray(select_topk(jnp.asarray(probs), topk, dim=1))
    assert mask.sum() == 8 * topk
    for i in range(8):
        top_idx = np.argsort(probs[i])[-topk:]
        assert mask[i, top_idx].all()


def test_auc_compute():
    x = jnp.asarray([0.0, 1.0])
    y = jnp.asarray([0.0, 1.0])
    np.testing.assert_allclose(float(_auc_compute(x, y)), 0.5)
    # decreasing x with direction auto-detect
    np.testing.assert_allclose(float(_auc_compute(x[::-1], y[::-1])), 0.5)


def test_normalize_logits_if_needed():
    probs = jnp.asarray([0.1, 0.9])
    np.testing.assert_allclose(np.asarray(normalize_logits_if_needed(probs, "sigmoid")), np.asarray(probs))
    logits = jnp.asarray([-2.0, 3.0])
    out = np.asarray(normalize_logits_if_needed(logits, "sigmoid"))
    np.testing.assert_allclose(out, 1 / (1 + np.exp(-np.asarray(logits))), atol=1e-6)


def test_dim_zero_cat():
    out = dim_zero_cat([jnp.asarray([1, 2]), jnp.asarray([3])])
    np.testing.assert_array_equal(np.asarray(out), [1, 2, 3])
