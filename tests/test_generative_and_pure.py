"""Tests for the fused PureCollection kernel and the generative image metrics
(FID/KID/IS/MiFID/LPIPS) — oracle parity via a shared fixed-weight extractor."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from tests.helpers import _assert_allclose
from tests.oracle import reference_torchmetrics

import torchmetrics_tpu as tm
from torchmetrics_tpu import MetricCollection
from torchmetrics_tpu.classification import (
    MulticlassAccuracy,
    MulticlassAUROC,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
)

_RNG = np.random.default_rng(7)
_W = _RNG.normal(size=(3 * 8 * 8, 16)).astype(np.float32)


class JnpExtractor:
    num_features = 16

    def __call__(self, imgs):
        x = jnp.asarray(imgs, jnp.float32).reshape(imgs.shape[0], -1)
        return x @ jnp.asarray(_W)


def _torch_extractor():
    import torch

    class TorchExtractor(torch.nn.Module):
        num_features = 16

        def forward(self, imgs):
            x = imgs.float().reshape(imgs.shape[0], -1)
            return x @ torch.from_numpy(_W)

    return TorchExtractor()


REAL = _RNG.random((48, 3, 8, 8)).astype(np.float32)
FAKE = (0.6 * REAL + 0.4 * _RNG.random((48, 3, 8, 8))).astype(np.float32)


def _oracle():
    tm_ref = reference_torchmetrics()
    if tm_ref is None:
        pytest.skip("oracle unavailable")
    import torch

    return tm_ref, torch


def test_fid_parity_shared_extractor():
    tm_ref, torch = _oracle()
    ours = tm.FrechetInceptionDistance(feature=JnpExtractor(), normalize=True)
    from torchmetrics.image.fid import FrechetInceptionDistance as RefFID  # type: ignore

    ref = RefFID(feature=_torch_extractor(), normalize=True)
    for arr, real in ((REAL, True), (FAKE, False), (REAL[:16] * 0.9, False)):
        ours.update(jnp.asarray(arr), real=real)
        ref.update(torch.as_tensor(arr), real=real)
    _assert_allclose(ours.compute(), ref.compute().numpy(), atol=1e-3)


def test_fid_merge_and_reset_real_features():
    single = tm.FrechetInceptionDistance(feature=JnpExtractor(), normalize=True)
    shards = [tm.FrechetInceptionDistance(feature=JnpExtractor(), normalize=True) for _ in range(2)]
    for i, arr in enumerate((REAL, FAKE)):
        single.update(jnp.asarray(REAL[i * 8 : (i + 1) * 8 + 16]), real=True)
        single.update(jnp.asarray(arr), real=False)
        shards[i].update(jnp.asarray(REAL[i * 8 : (i + 1) * 8 + 16]), real=True)
        shards[i].update(jnp.asarray(arr), real=False)
    shards[0].merge_state(shards[1])
    _assert_allclose(shards[0].compute(), single.compute(), atol=1e-3)

    keep = tm.FrechetInceptionDistance(feature=JnpExtractor(), normalize=True, reset_real_features=False)
    keep.update(jnp.asarray(REAL), real=True)
    keep.update(jnp.asarray(FAKE), real=False)
    n_real_before = int(keep._state["real_features_num_samples"])
    keep.reset()
    assert int(keep._state["real_features_num_samples"]) == n_real_before
    assert int(keep._state["fake_features_num_samples"]) == 0


def test_kid_parity_shared_extractor():
    tm_ref, torch = _oracle()
    # subsets draw randomly -> compare with subset_size == full size so MMD is exact
    ours = tm.KernelInceptionDistance(feature=JnpExtractor(), normalize=True, subsets=2, subset_size=48)
    from torchmetrics.image.kid import KernelInceptionDistance as RefKID  # type: ignore

    ref = RefKID(feature=_torch_extractor(), normalize=True, subsets=2, subset_size=48)
    ours.update(jnp.asarray(REAL), real=True)
    ours.update(jnp.asarray(FAKE), real=False)
    ref.update(torch.as_tensor(REAL), real=True)
    ref.update(torch.as_tensor(FAKE), real=False)
    ours_mean, ours_std = ours.compute()
    ref_mean, ref_std = ref.compute()
    # ours accumulates the MMD algebra in f64; the reference stays f32, and its
    # polynomial-kernel MMD at magnitude ~9e3 carries f32 cancellation noise up to
    # ~0.15 that shifts with accumulation order (run-order-dependent XLA tiling)
    _assert_allclose(ours_mean, ref_mean.numpy(), atol=0.25)
    assert float(ours_std) < 1e-6


def test_inception_score_parity_shared_extractor():
    tm_ref, torch = _oracle()
    # normalize=False: logits at unit scale keep exp(KL) finite in both trees
    ours = tm.InceptionScore(feature=JnpExtractor(), normalize=False, splits=2)
    from torchmetrics.image.inception import InceptionScore as RefIS  # type: ignore

    ref = RefIS(feature=_torch_extractor(), normalize=False, splits=2)
    ours.update(jnp.asarray(REAL * 0.05))
    ref.update(torch.as_tensor(REAL * 0.05))
    # both permute features before splitting; sidestep by checking against a
    # permutation-free recomputation of the same statistic
    ours_mean, _ = ours.compute()
    ref_mean, _ = ref.compute()
    assert float(ours_mean) == pytest.approx(float(ref_mean), rel=0.05)


def test_mifid_parity_shared_extractor():
    tm_ref, torch = _oracle()
    ours = tm.MemorizationInformedFrechetInceptionDistance(feature=JnpExtractor(), normalize=True)
    from torchmetrics.image.mifid import MemorizationInformedFrechetInceptionDistance as RefMiFID  # type: ignore

    ref = RefMiFID(feature=_torch_extractor(), normalize=True)
    ours.update(jnp.asarray(REAL), real=True)
    ours.update(jnp.asarray(FAKE), real=False)
    ref.update(torch.as_tensor(REAL), real=True)
    ref.update(torch.as_tensor(FAKE), real=False)
    _assert_allclose(ours.compute(), ref.compute().numpy(), atol=1e-2)


def test_int_feature_requires_weights():
    with pytest.raises(ModuleNotFoundError, match="converted InceptionV3 weights"):
        tm.FrechetInceptionDistance()
    with pytest.raises(ModuleNotFoundError, match="converted InceptionV3 weights"):
        tm.KernelInceptionDistance()


def test_lpips_machinery_invariants():
    lp = tm.LearnedPerceptualImagePatchSimilarity(pretrained=False)
    imgs = jnp.asarray(_RNG.random((2, 3, 64, 64)).astype(np.float32) * 2 - 1)
    other = jnp.asarray(_RNG.random((2, 3, 64, 64)).astype(np.float32) * 2 - 1)
    lp.update(imgs, imgs)
    assert float(lp.compute()) == pytest.approx(0.0, abs=1e-6)  # identical images
    lp2 = tm.LearnedPerceptualImagePatchSimilarity(pretrained=False)
    lp2.update(imgs, other)
    assert float(lp2.compute()) > 0.0
    with pytest.raises(ModuleNotFoundError, match="Pretrained LPIPS weights"):
        tm.LearnedPerceptualImagePatchSimilarity()


def test_inception_v3_shapes():
    from torchmetrics_tpu.image._extractors import InceptionV3Features

    inc = InceptionV3Features()
    out = inc(jnp.asarray(_RNG.random((2, 3, 299, 299)).astype(np.float32)))
    assert out.shape == (2, 2048)
    # integer input path + auto-resize
    out2 = inc(jnp.asarray(_RNG.integers(0, 255, (1, 3, 64, 64)).astype(np.uint8)))
    assert out2.shape == (1, 2048)


# -------------------------------------------------------------- PureCollection

def _make_collection():
    num_classes = 5
    return MetricCollection({
        "acc": MulticlassAccuracy(num_classes, average="micro", validate_args=False),
        "f1": MulticlassF1Score(num_classes, average="macro", validate_args=False),
        "auroc": MulticlassAUROC(num_classes, thresholds=50, validate_args=False),
        "confmat": MulticlassConfusionMatrix(num_classes, validate_args=False),
    })


def test_as_pure_matches_stateful_collection():
    rng = np.random.default_rng(3)
    batches = [
        (
            jax.nn.softmax(jnp.asarray(rng.normal(size=(64, 5)).astype(np.float32))),
            jnp.asarray(rng.integers(0, 5, 64, dtype=np.int32)),
        )
        for _ in range(3)
    ]
    stateful = _make_collection()
    for preds, target in batches:
        stateful.update(preds, target)
    expected = stateful.compute()

    pure = _make_collection().as_pure()
    step = jax.jit(pure.update, donate_argnums=0)
    states = pure.init()
    for preds, target in batches:
        states = step(states, preds, target)
    values = jax.jit(pure.compute)(states)
    assert set(values) == set(expected)
    _assert_allclose(values, expected, atol=1e-5)


def test_as_pure_in_graph_sharded():
    from torchmetrics_tpu.parallel import shard_map

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = jax.sharding.Mesh(np.array(devices[:8]), ("data",))
    rng = np.random.default_rng(4)
    preds = jax.nn.softmax(jnp.asarray(rng.normal(size=(64, 5)).astype(np.float32)))
    target = jnp.asarray(rng.integers(0, 5, 64, dtype=np.int32))

    pure = _make_collection().as_pure()

    def shard_step(p, t):
        local = pure.update(pure.init(), p, t)
        return pure.reduce(local, "data")

    fn = jax.jit(shard_map(shard_step, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P()))
    synced = fn(preds, target)
    sharded_values = pure.compute(synced)

    single = _make_collection()
    single.update(preds, target)
    _assert_allclose(sharded_values, single.compute(), atol=1e-5)


def test_as_pure_rejects_list_state_metrics():
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    coll = MetricCollection({"cat": tm.CatMetric()})
    pure = coll.as_pure()
    with pytest.raises(TorchMetricsUserError):
        pure.update(pure.init(), jnp.zeros(4))


def test_device_counter_running_mean_exact():
    """Regression: the on-device update counter keeps 'mean' states exact."""
    m = tm.MeanMetric()
    vals = [1.0, 5.0, 9.0, 11.0]
    for v in vals:
        m.update(jnp.asarray(v))
    assert float(m.compute()) == pytest.approx(np.mean(vals))
    m.reset()
    for v in vals[:2]:
        m.update(jnp.asarray(v))
    assert float(m.compute()) == pytest.approx(np.mean(vals[:2]))


def test_dists_machinery_invariants():
    imgs = jnp.asarray(_RNG.random((2, 3, 64, 64)).astype(np.float32))
    m = tm.DeepImageStructureAndTextureSimilarity(pretrained=False)
    m.update(imgs, imgs)
    assert float(m.compute()) == pytest.approx(0.0, abs=1e-5)  # identical images
    m2 = tm.DeepImageStructureAndTextureSimilarity(pretrained=False)
    m2.update(imgs, jnp.asarray(_RNG.random((2, 3, 64, 64)).astype(np.float32)))
    assert float(m2.compute()) > 0.0
    with pytest.raises(ModuleNotFoundError, match="DISTS weights"):
        tm.DeepImageStructureAndTextureSimilarity()


def test_perceptual_path_length_machinery():
    rng = np.random.default_rng(3)
    proj = jnp.asarray(rng.normal(size=(8, 3 * 16 * 16)).astype(np.float32) * 0.1)

    class ToyGen:
        def sample(self, n):
            return rng.normal(size=(n, 8)).astype(np.float32)

        def __call__(self, z):
            img = jax.nn.sigmoid(jnp.asarray(z) @ proj)
            return 255 * img.reshape(-1, 3, 16, 16)

    def toy_sim(a, b):
        return jnp.abs(a - b).mean(axis=(1, 2, 3))

    mean, std, dist = tm.functional.perceptual_path_length(
        ToyGen(), num_samples=48, batch_size=16, sim_net=toy_sim, resize=None
    )
    assert dist.shape == (48,)
    assert float(mean) > 0 and float(std) >= 0
    # smooth generator: distances scale ~1/eps^2 * (eps-step)^2 => finite, stable
    m = tm.PerceptualPathLength(num_samples=32, batch_size=16, sim_net=toy_sim, resize=None)
    m.update(ToyGen())
    mm, ss, dd = m.compute()
    assert dd.shape == (32,)
    with pytest.raises(NotImplementedError, match="sample"):
        tm.functional.perceptual_path_length(object(), num_samples=4, sim_net=toy_sim)
