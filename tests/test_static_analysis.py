"""graftlint: golden-fixture coverage + real-tree cleanliness + drift gates.

Marker ``lint``. The static tests are stdlib-only (the linter never imports
the package under analysis); only the runtime cross-validation of the
plane-admissibility matrix needs jax.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES_DIR = os.path.join(REPO_ROOT, "tests", "_lint_fixtures")
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftlint.admissibility import build_matrix  # noqa: E402
from tools.graftlint.astindex import PackageIndex  # noqa: E402
from tools.graftlint.baseline import (  # noqa: E402
    load_baseline,
    parse_baseline,
    resolve_against_baseline,
)
from tools.graftlint.docgen import check_docs  # noqa: E402
from tools.graftlint.layout import (  # noqa: E402
    check_fleet_layout,
    parse_int_assign,
    parse_str_tuple,
)
from tools.graftlint.model import build_models  # noqa: E402
from tools.graftlint.registry import check_registry  # noqa: E402
from tools.graftlint.runner import build_index, run_checks  # noqa: E402
from tools.graftlint.tracer import check_tracer_hygiene  # noqa: E402


def _fixture_index() -> PackageIndex:
    return PackageIndex(FIXTURES_DIR, "_lint_fixtures")


def _read(relpath: str) -> str:
    with open(os.path.join(REPO_ROOT, relpath), "r", encoding="utf-8") as fh:
        return fh.read()


def _ledger() -> dict:
    return json.loads(_read("tools/graftlint/layout_ledger.json"))


COUNTERS_SRC = _read("torchmetrics_tpu/observability/counters.py")
HISTOGRAMS_SRC = _read("torchmetrics_tpu/observability/histograms.py")
COALESCE_SRC = _read("torchmetrics_tpu/parallel/coalesce.py")
EVENTS_SRC = _read("torchmetrics_tpu/observability/events.py")
OBS_MD = _read("docs/observability.md")


# --------------------------------------------------------------------- gate

def test_repo_is_clean_against_baseline():
    """THE tier-1 gate: the full pass over the real tree resolves clean
    against the committed baseline (new findings / stale or unjustified
    baseline entries all fail)."""
    findings, _ = run_checks(REPO_ROOT)
    entries, fmt_errors = load_baseline(
        os.path.join(REPO_ROOT, "tools", "graftlint", "baseline.txt"))
    assert not fmt_errors, fmt_errors
    res = resolve_against_baseline(findings, entries)
    msgs = [f.render() for f in res["new"]]
    assert not res["new"], "new graftlint findings:\n" + "\n".join(msgs)
    assert not res["stale"], f"stale baseline entries: {[e.fingerprint for e in res['stale']]}"
    assert not res["unjustified"], (
        f"unjustified baseline entries: {[e.fingerprint for e in res['unjustified']]}")


def test_cli_check_exit_zero_on_repo():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--check"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_check_exit_nonzero_on_fixtures(tmp_path):
    """Exit-code contract: each golden-fixture family makes --check fail."""
    empty_baseline = tmp_path / "baseline.txt"
    empty_baseline.write_text("")
    for family in ("tracer", "registry"):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "--check",
             "--root", os.path.join(REPO_ROOT, "tests"),
             "--package", "_lint_fixtures",
             "--baseline", str(empty_baseline),
             "--family", family],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 1, (family, proc.stdout, proc.stderr)


# ---------------------------------------------------------- tracer hygiene

def test_tracer_fixture_fires_every_rule():
    idx = _fixture_index()
    findings = check_tracer_hygiene(idx, build_models(idx))
    rules = {f.rule for f in findings if "viol_tracer" in f.path}
    assert rules == {"tracer/item", "tracer/coercion", "tracer/numpy-call", "tracer/py-branch"}, (
        sorted(f.render() for f in findings))
    # and each anchors on the offending method
    assert all(f.symbol == "ItemLeak._batch_state" for f in findings if "viol_tracer" in f.path)


def test_tracer_clean_on_real_tree():
    findings, _ = run_checks(REPO_ROOT, families=("tracer",))
    tracer = [f for f in findings if f.rule.startswith("tracer/")]
    assert tracer == [], "\n".join(f.render() for f in tracer)


# ----------------------------------------------------------------- registry

def test_registry_fixture_fires():
    idx = _fixture_index()
    findings = check_registry(idx)
    rules = {f.rule for f in findings}
    assert "registry/reserved-key" in rules
    assert "registry/reserved-prefix" in rules
    assert "registry/unregistered-tag" in rules
    byrule = {f.rule: f for f in findings}
    assert byrule["registry/reserved-key"].detail == "__tenant_n"
    assert byrule["registry/unregistered-tag"].detail == "zupdate"


def test_registry_clean_on_real_tree():
    idx = build_index(REPO_ROOT)
    findings = check_registry(idx)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_registered_tags_match_runtime_set():
    """The statically parsed tag registry is exactly the twelve runtime planes
    (ISSUE 12 added the tiered-window tags wdual/wstack/vwupdate/vwcompute;
    ISSUE 20 the re-homed evaluator tags mapeval/escore)."""
    from tools.graftlint.registry import registered_tags, reserved_keys
    idx = build_index(REPO_ROOT)
    assert registered_tags(idx) == {
        "update", "forward", "vupdate", "wupdate", "wdual", "wstack",
        "vwupdate", "vwcompute", "dupdate", "vcompute", "mapeval", "escore",
    }
    assert reserved_keys(idx) == {
        "__tenant_n", "__window_cursor", "__window_n", "__decay_n",
        # two-stack window accumulator PREFIXES (each real state name k gets
        # companion leaves under prefix+k; the `__` near-miss check covers
        # the whole namespace — the dual tier packs its pair under the
        # state's own name and needs no reserved prefix)
        "__window_front:", "__window_back:", "__window_bagg:",
        # quantized sync plane's error-feedback residual namespace (ISSUE 13;
        # mirrors parallel.quantize.RESIDUAL_KEY_PREFIX, pinned equal in
        # tests/test_quantized_sync.py)
        "__quant_err:",
    }


# ------------------------------------------------------------- fleet layout

def test_layout_clean_on_real_tree():
    findings = check_fleet_layout(
        COUNTERS_SRC, HISTOGRAMS_SRC, COALESCE_SRC, EVENTS_SRC, _ledger(), OBS_MD)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_counter_growth_without_version_bump_is_caught():
    """THE acceptance scenario: mutate a copy of COUNTER_FIELDS, keep
    _VERSION — the drift check must fire."""
    mutated = COUNTERS_SRC.replace(
        '"serve_rejected",', '"serve_rejected",\n    "graftlint_probe_counter",')
    assert mutated != COUNTERS_SRC
    findings = check_fleet_layout(
        mutated, HISTOGRAMS_SRC, COALESCE_SRC, EVENTS_SRC, _ledger(), OBS_MD)
    assert any(f.rule == "layout/counter-drift" for f in findings), (
        [f.rule for f in findings])


def test_histogram_growth_without_version_bump_is_caught():
    mutated = HISTOGRAMS_SRC.replace(
        '"gather_bytes",', '"gather_bytes",\n    "graftlint_probe_kind",')
    assert mutated != HISTOGRAMS_SRC
    findings = check_fleet_layout(
        COUNTERS_SRC, mutated, COALESCE_SRC, EVENTS_SRC, _ledger(), OBS_MD)
    assert any(f.rule == "layout/hist-drift" for f in findings)


def test_version_bump_without_ledger_is_caught():
    led = _ledger()
    version = parse_int_assign(COALESCE_SRC, "_VERSION")
    mutated = COALESCE_SRC.replace(f"_VERSION = {version}", f"_VERSION = {version + 1}", 1)
    assert mutated != COALESCE_SRC
    findings = check_fleet_layout(
        COUNTERS_SRC, HISTOGRAMS_SRC, mutated, EVENTS_SRC, led, OBS_MD)
    assert any(f.rule == "layout/ledger-stale" for f in findings)


def test_undocumented_counter_is_caught():
    """Doc-drift: a counter missing from docs/observability.md fails."""
    led = _ledger()
    led["counter_fields"] = led["counter_fields"] + ["graftlint_probe_counter"]
    mutated = COUNTERS_SRC.replace(
        '"serve_rejected",', '"serve_rejected",\n    "graftlint_probe_counter",')
    findings = check_fleet_layout(
        mutated, HISTOGRAMS_SRC, COALESCE_SRC, EVENTS_SRC, led, OBS_MD)
    assert any(f.rule == "layout/doc-counter" and f.detail == "graftlint_probe_counter"
               for f in findings)


def test_ledger_matches_sources_exactly():
    led = _ledger()
    assert led["counter_fields"] == parse_str_tuple(COUNTERS_SRC, "COUNTER_FIELDS")
    assert led["histogram_kinds"] == parse_str_tuple(HISTOGRAMS_SRC, "FLEET_HISTOGRAM_KINDS")
    assert led["version"] == parse_int_assign(COALESCE_SRC, "_VERSION")


# ------------------------------------------------------------ admissibility

def test_fixture_admissibility_rows():
    idx = _fixture_index()
    matrix = build_matrix(build_models(idx))
    rows = matrix["metrics"]
    cat = rows["_lint_fixtures.viol_plane.ConcatStateMetric"]["planes"]
    assert cat["vupdate"] == "no" and cat["dupdate"] == "no" and cat["ingraph"] == "no"
    # a LIST cat state rides SlidingWindow's bounded host ring
    assert cat["wupdate"] == "yes"
    mean = rows["_lint_fixtures.viol_plane.BareMeanMetric"]["planes"]
    assert mean["ingraph"] == "no" and mean["vupdate"] == "yes"
    clean = rows["_lint_fixtures.viol_plane.CleanMetric"]["planes"]
    assert set(clean.values()) == {"yes"}
    host = rows["_lint_fixtures.viol_plane.HostSideMetric"]["planes"]
    assert host["vcompute"] == "no" and host["vupdate"] == "yes"


def test_docs_matrix_tables_in_sync():
    _, matrix = run_checks(REPO_ROOT, families=("registry",))  # cheap family; matrix always built
    findings = check_docs(matrix, REPO_ROOT)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_matrix_covers_known_classes():
    _, matrix = run_checks(REPO_ROOT, families=("registry",))
    rows = matrix["metrics"]
    for cls in (
        "torchmetrics_tpu.aggregation.MeanMetric",
        "torchmetrics_tpu.classification.accuracy.MulticlassAccuracy",
        "torchmetrics_tpu.classification.confusion_matrix.MulticlassConfusionMatrix",
        "torchmetrics_tpu.regression.pearson.PearsonCorrCoef",
    ):
        assert cls in rows, f"{cls} missing from the admissibility matrix"
    # wrappers/framework bases are excluded, not misclassified
    assert "torchmetrics_tpu.wrappers.running.Running" in matrix["excluded_abstract_or_wrapper"]


def test_matrix_runtime_cross_validation():
    """The static verdicts agree with the real runtime guards on a sample."""
    pytest.importorskip("jax")
    from torchmetrics_tpu.aggregation import MeanMetric
    from torchmetrics_tpu.classification import BinaryAUROC, MulticlassConfusionMatrix
    from torchmetrics_tpu.regression import PearsonCorrCoef
    from torchmetrics_tpu.streaming import ExponentialDecay
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    _, matrix = run_checks(REPO_ROOT, families=("registry",))
    rows = matrix["metrics"]

    # vupdate yes -> the stacked program materializes
    assert rows["torchmetrics_tpu.aggregation.MeanMetric"]["planes"]["vupdate"] == "yes"
    MeanMetric()._get_vupdate_fn()
    assert rows["torchmetrics_tpu.classification.confusion_matrix.MulticlassConfusionMatrix"][
        "planes"]["vupdate"] == "yes"
    MulticlassConfusionMatrix(num_classes=3)._get_vupdate_fn()

    # dupdate no (custom _merge) -> ExponentialDecay rejects at construction
    assert rows["torchmetrics_tpu.regression.pearson.PearsonCorrCoef"]["planes"]["dupdate"] == "no"
    with pytest.raises(TorchMetricsUserError):
        ExponentialDecay(PearsonCorrCoef(), decay=0.5)
    # dupdate yes -> accepted
    assert rows["torchmetrics_tpu.aggregation.MeanMetric"]["planes"]["dupdate"] == "yes"
    ExponentialDecay(MeanMetric(), decay=0.5)

    # "?" = config-conditional: BOTH runtime outcomes are reachable
    assert rows["torchmetrics_tpu.classification.auroc.BinaryAUROC"]["planes"]["vupdate"] == "?"
    with pytest.raises(TorchMetricsUserError):
        BinaryAUROC()._get_vupdate_fn()  # thresholds=None -> cat list state
    BinaryAUROC(thresholds=16)._get_vupdate_fn()  # binned -> static state


def test_matrix_window_tier_cross_validation():
    """The static window-tier column (ISSUE 12) agrees with the runtime
    `metric.window_tier` derivation and the windowed-serving guard."""
    pytest.importorskip("jax")
    from torchmetrics_tpu.aggregation import CatMetric, MaxMetric, MeanMetric, SumMetric
    from torchmetrics_tpu.classification import MulticlassConfusionMatrix
    from torchmetrics_tpu.metric import window_tier
    from torchmetrics_tpu.regression import PearsonCorrCoef
    from torchmetrics_tpu.serving import ServingConfig, ServingEngine
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    _, matrix = run_checks(REPO_ROOT, families=("registry",))
    rows = matrix["metrics"]
    pairs = [
        ("torchmetrics_tpu.aggregation.SumMetric", SumMetric()),
        ("torchmetrics_tpu.aggregation.MeanMetric", MeanMetric()),
        ("torchmetrics_tpu.aggregation.MaxMetric", MaxMetric()),
        ("torchmetrics_tpu.classification.confusion_matrix.MulticlassConfusionMatrix",
         MulticlassConfusionMatrix(num_classes=3, validate_args=False)),
        ("torchmetrics_tpu.regression.pearson.PearsonCorrCoef", PearsonCorrCoef()),
    ]
    for qual, inst in pairs:
        assert rows[qual]["window_tier"] == window_tier(inst), qual
    # CatMetric's states are config-conditional (nan_strategy) -> static "?",
    # while this concrete construction lands in the ring tier at runtime
    assert rows["torchmetrics_tpu.aggregation.CatMetric"]["window_tier"] in ("ring", "?")
    assert window_tier(CatMetric()) == "ring"
    # vwupdate verdicts mirror the windowed-engine construction guard
    assert rows["torchmetrics_tpu.classification.confusion_matrix.MulticlassConfusionMatrix"][
        "planes"]["vwupdate"] == "yes"
    ServingEngine(MulticlassConfusionMatrix(num_classes=3, validate_args=False),
                  ServingConfig(capacity=4, megabatch_size=2, window=4))
    assert rows["torchmetrics_tpu.regression.pearson.PearsonCorrCoef"]["planes"]["vwupdate"] == "no"
    with pytest.raises(TorchMetricsUserError):
        ServingEngine(PearsonCorrCoef(), ServingConfig(capacity=4, megabatch_size=2, window=4))
    # the matrix carries fleet-wide tier totals for the doc rollup
    totals = matrix["window_tier_totals"]
    assert set(totals) == {"dual", "two_stack", "ring", "?"}
    assert sum(totals.values()) == len(rows)


def test_matrix_runtime_cross_validation_host_metric():
    pytest.importorskip("jax")
    from torchmetrics_tpu.aggregation import SumMetric
    from torchmetrics_tpu.streaming import SlidingWindow
    from torchmetrics_tpu.text import ROUGEScore
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    _, matrix = run_checks(REPO_ROOT, families=("registry",))
    rows = matrix["metrics"]
    assert rows["torchmetrics_tpu.text.metrics.ROUGEScore"]["planes"]["wupdate"] == "no"
    with pytest.raises(TorchMetricsUserError):
        SlidingWindow(ROUGEScore(), window=4)
    assert rows["torchmetrics_tpu.aggregation.SumMetric"]["planes"]["wupdate"] == "yes"
    SlidingWindow(SumMetric(), window=4)


def test_matrix_runtime_cross_validation_rehomed_metrics():
    """ISSUE 20 flipped rows: DeviceMeanAveragePrecision enters the matrix and
    CLIPScore leaves the not-admissible-everywhere tables. Every flipped
    verdict is cross-validated against the real runtime guard."""
    pytest.importorskip("jax")
    import jax.numpy as jnp
    import numpy as np

    from torchmetrics_tpu.detection import DeviceMeanAveragePrecision
    from torchmetrics_tpu.multimodal import CLIPScore
    from torchmetrics_tpu.serving import ServingConfig, ServingEngine
    from torchmetrics_tpu.streaming import ExponentialDecay, SlidingWindow
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    emb = np.random.default_rng(0).standard_normal((64, 8)).astype(np.float32)

    class ToyClip:
        def get_image_features(self, images):
            flat = jnp.stack([jnp.asarray(i, jnp.float32).reshape(-1)[:12] for i in images])
            return flat @ jnp.asarray(emb[:12])

        def get_text_features(self, texts):
            return jnp.stack([jnp.asarray(emb[[hash(w) % 64 for w in t.split()]]).sum(axis=0)
                              for t in texts])

    _, matrix = run_checks(REPO_ROOT, families=("registry",))
    rows = matrix["metrics"]

    dev_row = rows["torchmetrics_tpu.detection.mean_ap.DeviceMeanAveragePrecision"]
    assert dev_row["planes"] == {
        "vupdate": "yes", "vcompute": "no", "vwupdate": "no", "wupdate": "yes",
        "dupdate": "no", "tenant_sharding": "yes", "ingraph": "yes",
    }
    assert dev_row["window_tier"] == "ring"
    dev = lambda: DeviceMeanAveragePrecision(capacity=64, num_classes=3)  # noqa: E731
    dev()._get_vupdate_fn()  # vupdate yes: stacked program materializes
    assert dev()._jittable_compute is False  # vcompute no: host-side _compute
    SlidingWindow(dev(), window=4)  # wupdate yes
    with pytest.raises(TorchMetricsUserError):  # dupdate no: custom _merge
        ExponentialDecay(dev(), decay=0.5)
    ServingEngine(dev(), ServingConfig(capacity=4, megabatch_size=2))  # sharding yes
    with pytest.raises(TorchMetricsUserError):  # vwupdate no: ring window tier
        ServingEngine(dev(), ServingConfig(capacity=4, megabatch_size=2, window=4))

    clip_row = rows["torchmetrics_tpu.multimodal.clip_score.CLIPScore"]
    assert all(v == "yes" for v in clip_row["planes"].values()), clip_row["planes"]
    assert clip_row["window_tier"] == "dual"
    clip = lambda: CLIPScore(model_name_or_path=ToyClip())  # noqa: E731
    clip()._get_vupdate_fn()
    assert clip()._jittable_compute is True
    SlidingWindow(clip(), window=4)
    ExponentialDecay(clip(), decay=0.5)
    ServingEngine(clip(), ServingConfig(capacity=4, megabatch_size=2, window=4))


# ----------------------------------------------------------------- baseline

def test_baseline_mechanics(tmp_path):
    from tools.graftlint.core import Finding
    f1 = Finding("tracer/item", "pkg/a.py", "Cls._batch_state", "item()", "msg", 10)
    f2 = Finding("tracer/item", "pkg/b.py", "Cls2._batch_state", "item()", "msg", 20)
    entries, errors = parse_baseline(
        f"{f1.fingerprint}  # validated eager-only path\n"
        f"{f2.fingerprint}  # TODO: justify\n"
        "tracer/item|gone.py|X.y|item()  # fixed long ago\n"
        "malformed-line-without-pipes  # nope\n")
    assert len(errors) == 1  # the malformed line
    res = resolve_against_baseline([f1, f2], entries)
    assert res["new"] == []
    assert len(res["baselined"]) == 2
    assert [e.fingerprint for e in res["stale"]] == ["tracer/item|gone.py|X.y|item()"]
    assert [e.fingerprint for e in res["unjustified"]] == [f2.fingerprint]


def test_family_subset_does_not_mark_other_families_stale(tmp_path):
    """--family runs must only resolve the selected families' baseline
    entries — an unselected family's live suppression is not 'stale'."""
    baseline = tmp_path / "baseline.txt"
    # a justified tracer entry matching the fixture violation, which the
    # layout-only run does NOT produce findings for
    baseline.write_text(
        "tracer/item|_lint_fixtures/viol_tracer.py|ItemLeak._batch_state|item()"
        "  # documented fixture violation\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--check",
         "--root", os.path.join(REPO_ROOT, "tests"),
         "--package", "_lint_fixtures",
         "--baseline", str(baseline),
         "--family", "plane"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
    assert "[baseline/stale]" not in proc.stdout, proc.stdout
    # and the tracer-family run still honors (and consumes) the entry
    proc2 = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         "--root", os.path.join(REPO_ROOT, "tests"),
         "--package", "_lint_fixtures",
         "--baseline", str(baseline),
         "--family", "tracer"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
    assert "1 baselined" in proc2.stdout and "0 stale" in proc2.stdout, proc2.stdout


def test_group_range_validation_rejects_id_equal_to_num_groups():
    """Group ids are 0..num_groups-1: id == num_groups must raise (eagerly)."""
    pytest.importorskip("jax")
    import jax.numpy as jnp
    from torchmetrics_tpu.functional.classification.group_fairness import _groups_validation
    with pytest.raises(ValueError):
        _groups_validation(jnp.asarray([0, 1, 2]), num_groups=2)
    _groups_validation(jnp.asarray([0, 1]), num_groups=2)  # in range: fine


def test_fingerprint_excludes_line_numbers():
    from tools.graftlint.core import Finding
    a = Finding("r", "p.py", "S.m", "d", "msg", 1)
    b = Finding("r", "p.py", "S.m", "d", "other msg", 999)
    assert a.fingerprint == b.fingerprint


# ------------------------------------------------------- bench integration

def test_bench_compare_lint_findings_is_informational():
    """The lint_findings column is tracked but never gated (a lint-count
    move is not a perf regression)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_compare_for_lint", os.path.join(REPO_ROOT, "tools", "bench_compare.py"))
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)
    assert bc.direction("extra.lint_findings") is None
    rows = bc.compare_metrics({"extra.lint_findings": 0.0}, {"extra.lint_findings": 25.0})
    assert rows[0]["verdict"] == "info"
