"""Fleet failover plane tests (torchmetrics_tpu/fleet). Marker ``fleet``.

The load-bearing claims, each pinned:

- **placement**: the weighted rendezvous map is deterministic, respects
  weights, and a host join/leave produces the MINIMAL move set — only
  tenants whose rendezvous winner actually changed relocate;
- **membership**: leases walk alive → suspect → dead on the injected
  clock; a suspect that revives causes NO spurious failover (the flap
  window), expiry reports exactly once, and a rejoin after expiry bumps
  the liveness epoch (the coalesce-v8 discipline);
- **migration kill-point fuzz**: a kill at EVERY protocol stage boundary —
  drain, snapshot, transfer (including a torn transferred artifact),
  restore — aborts cleanly: every tenant whole on exactly one host,
  digests untouched, no residual artifacts; a kill after cutover is
  post-commit and the destination owns everything;
- **failover**: lease expiry makes survivors adopt the dead host's tenants
  from its latest snapshot generation + journal tail, bitwise
  (restore + replay = pre-crash state), with RPO 0 at ``fsync_every=1``,
  and a tenant first seen inside the suspicion window is re-placed, not
  lost;
- **bounded retention** (satellite): ``SnapshotStore.prune`` never removes
  the newest generation, and a store pruned to ``keep_last=1`` with its
  covered journal segments swept still restores + replays to parity;
- **the fleet soak**: ``run_soak(fleet_hosts=N)`` with ``host_loss`` +
  ``host_join`` ends at per-tenant parity 1.0 against an uninterrupted
  single-host reference, zero double counts, and a byte-identical counter
  block on a second run.
"""

from __future__ import annotations

import dataclasses
import os
import warnings

import numpy as np
import pytest

from torchmetrics_tpu.chaos import (
    FaultSchedule,
    FaultSpec,
    SoakConfig,
    TrafficConfig,
    run_soak,
)
from torchmetrics_tpu.classification import MulticlassAccuracy
from torchmetrics_tpu.fleet import (
    MIGRATION_STAGES,
    FleetController,
    LeaseConfig,
    Membership,
    MigrationAborted,
    Move,
    place,
    place_all,
    placement_score,
    rebalance_plan,
    tenant_state_digest,
)
from torchmetrics_tpu.serving import ServingConfig, ServingEngine, SnapshotStore
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

pytestmark = pytest.mark.fleet

NUM_CLASSES = 3
BATCH = 4


def _metric():
    return MulticlassAccuracy(NUM_CLASSES, average="micro", validate_args=False)


def _batch(i: int):
    rng = np.random.default_rng(1000 + i)
    preds = rng.normal(size=(BATCH, NUM_CLASSES)).astype(np.float32)
    target = rng.integers(0, NUM_CLASSES, BATCH, dtype=np.int32)
    return preds, target


def _serving(**kw) -> ServingConfig:
    base = dict(capacity=16, megabatch_size=4, journal_fsync_every=1)
    base.update(kw)
    return ServingConfig(**base)


def _fleet(tmp_path, hosts=3, clock=None, lease=None, **serving_kw):
    return FleetController(
        _metric,
        root=str(tmp_path / "fleet"),
        hosts=hosts,
        serving=_serving(**serving_kw),
        lease=lease,
        clock=clock,
    )


def _expire(fc, clock, until=7.0, step=1.0):
    """Advance the virtual clock in heartbeat-sized ticks (live hosts renew,
    killed hosts stay silent) until the victim's lease expires; returns every
    host poll() failed over along the way."""
    failed = []
    while clock["t"] < until:
        clock["t"] += step
        fc.heartbeat_all()
        failed += fc.poll()
    return failed


def _roster_count(controller, tid) -> int:
    """On how many live engines does ``tid`` hold state? (exactly-one gate)"""
    return sum(
        1
        for h in controller._hosts.values()
        if not h.killed and tid in h.engine.tenants()
    )


# ------------------------------------------------------------------ placement


def test_placement_deterministic_and_total():
    hosts = {"a": 1.0, "b": 1.0, "c": 1.0}
    for tid in range(50):
        first = place(tid, hosts)
        assert first in hosts
        assert all(place(tid, hosts) == first for _ in range(3))
    assignment = place_all(range(50), hosts)
    assert assignment == {tid: place(tid, hosts) for tid in range(50)}
    # every host wins something at this size (rendezvous spreads)
    assert set(assignment.values()) == set(hosts)


def test_placement_score_positive_and_weighted():
    assert placement_score("a", 7) > 0
    # the -w/ln(u) transform scales expected share linearly in weight: over
    # many tenants the weight-3 host must own strictly more than a weight-1
    counts = {"light": 0, "heavy": 0}
    for tid in range(400):
        counts[place(tid, {"light": 1.0, "heavy": 3.0})] += 1
    assert counts["heavy"] > counts["light"]
    with pytest.raises(TorchMetricsUserError):
        place(0, {})


def test_rebalance_join_is_minimal():
    hosts = {"a": 1.0, "b": 1.0}
    assignment = place_all(range(60), hosts)
    grown = dict(hosts, c=1.0)
    plan = rebalance_plan(assignment, grown)
    assert plan  # the new host gets its fair share
    for move in plan:
        assert isinstance(move, Move)
        assert move.dst == "c"  # join moves ONLY onto the joiner
        assert move.src == assignment[move.tenant_id]
        assert place(move.tenant_id, grown) == "c"
    # everything not in the plan keeps its seat under the grown map
    moved = {m.tenant_id for m in plan}
    for tid, host in assignment.items():
        if tid not in moved:
            assert place(tid, grown) == host


def test_rebalance_leave_moves_only_the_leaver():
    hosts = {"a": 1.0, "b": 1.0, "c": 1.0}
    assignment = place_all(range(60), hosts)
    shrunk = {h: w for h, w in hosts.items() if h != "c"}
    plan = rebalance_plan(assignment, shrunk)
    assert {m.tenant_id for m in plan} == {
        tid for tid, host in assignment.items() if host == "c"
    }
    for move in plan:
        # the old owner is gone from the map: src is None by contract (the
        # adoption form a failover consumes), and the seat is a survivor
        assert move.src is None and move.dst in shrunk


# ----------------------------------------------------------------- membership


def test_lease_state_machine_and_flap():
    clock = {"t": 0.0}
    m = Membership(lambda: clock["t"], LeaseConfig(
        heartbeat_interval=1.0, suspect_after=3.0, dead_after=6.0,
    ))
    m.join("h0")
    assert m.state("h0") == "alive"
    clock["t"] = 4.0
    assert m.state("h0") == "suspect"
    # the flap: a suspect that heartbeats revives with NO expiry reported
    m.heartbeat("h0")
    assert m.state("h0") == "alive"
    assert m.expire() == []
    # silence past dead_after expires exactly once
    clock["t"] = 11.0
    assert m.state("h0") == "dead"
    assert m.expire() == ["h0"]
    assert m.expire() == []
    # dead hosts are out of the placement map; heartbeats cannot resurrect
    assert "h0" not in m.hosts()
    m.heartbeat("h0")
    assert m.state("h0") == "dead"
    # rejoin is a NEW incarnation: epoch bumps (coalesce-v8 discipline)
    member = m.join("h0")
    assert member.epoch == 2
    assert m.state("h0") == "alive"


def test_lease_config_validation():
    with pytest.raises(ValueError):
        LeaseConfig(suspect_after=5.0, dead_after=4.0)
    with pytest.raises(ValueError):
        LeaseConfig(heartbeat_interval=0.0)
    with pytest.raises(TorchMetricsUserError):
        Membership(clock=None)  # type: ignore[arg-type]


def test_suspect_keeps_tenants_no_spurious_failover(tmp_path):
    """A host that merely misses heartbeats (never crashed) keeps serving its
    tenants, and poll() must not fail it over before the lease expires."""
    clock = {"t": 0.0}
    fc = _fleet(tmp_path, hosts=2, clock=lambda: clock["t"],
                lease=LeaseConfig(suspect_after=2.0, dead_after=5.0))
    for i in range(8):
        fc.serve(i, *_batch(i))
    fc.flush()
    before = fc.tenant_digests()
    # only host-0 heartbeats; host-1 goes silent into the suspect window
    clock["t"] = 3.0
    fc.membership.heartbeat("host-0")
    assert fc.hosts()["host-1"] == "suspect"
    assert fc.poll() == []  # suspect != dead: no failover
    # routing still targets the suspect — traffic lands on its engine
    suspect_tenants = [t for t, h in fc.tenants().items() if h == "host-1"]
    assert suspect_tenants, "rendezvous should seat someone on host-1"
    assert fc.serve(suspect_tenants[0], *_batch(99))
    # the flap resolves: host-1 heartbeats again, nothing moved
    fc.membership.heartbeat("host-1")
    assert fc.hosts()["host-1"] == "alive"
    assert fc.stats["failovers"] == 0
    after = fc.tenant_digests()
    for tid in before:
        if tid != suspect_tenants[0]:
            assert after[tid] == before[tid]
    fc.close()


# ------------------------------------------------------- migration kill fuzz


class _Boom(RuntimeError):
    pass


def test_migration_stages_are_the_contract():
    assert MIGRATION_STAGES == ("drain", "snapshot", "transfer", "restore", "cutover")


@pytest.mark.parametrize("stage", [s for s in MIGRATION_STAGES if s != "cutover"])
def test_migration_kill_point_fuzz(tmp_path, stage):
    """A kill at every pre-commit stage boundary aborts cleanly: ownership
    never flips, the destination holds nothing, digests are untouched, no
    transfer artifact survives — then the SAME migration succeeds."""
    fc = _fleet(tmp_path, hosts=2)
    for i in range(10):
        fc.serve(i, *_batch(i))
    fc.flush()
    victims = [t for t, h in fc.tenants().items() if h == "host-0"][:3]
    assert victims
    before_digests = fc.tenant_digests()
    before_owner = dict(fc.tenants())

    def hook(s):
        if s == stage:
            raise _Boom(f"killed at {s}")

    with pytest.raises(MigrationAborted) as err:
        fc.migrate(victims, "host-1", _stage_hook=hook)
    assert isinstance(err.value.__cause__, _Boom)
    # nothing moved, nothing lost, nothing duplicated
    assert fc.tenants() == before_owner
    assert fc.tenant_digests() == before_digests
    for tid in victims:
        assert _roster_count(fc, tid) == 1
    for h in fc._hosts.values():
        for box in (h.outbox_dir, h.inbox_dir):
            assert not (os.path.isdir(box) and SnapshotStore(box).generations()), (
                f"stage {stage!r} left a transfer artifact in {box}"
            )
    assert fc.stats["aborted_migrations"] == 1
    assert fc.stats["migrated_tenants"] == 0
    # the protocol is re-runnable after the abort: same move, clean commit
    out = fc.migrate(victims, "host-1")
    assert out["moved"] == len(victims) and out["parity_failures"] == 0
    after = fc.tenant_digests()
    for tid in victims:
        assert fc.tenants()[tid] == "host-1"
        assert after[tid] == before_digests[tid]
        assert _roster_count(fc, tid) == 1
    fc.close()


def test_migration_torn_transfer_artifact_aborts(tmp_path):
    """A transfer that tears mid-copy is caught by the artifact's sha256 at
    restore-on-dst — the migration aborts with the source authoritative."""
    fc = _fleet(tmp_path, hosts=2)
    for i in range(8):
        fc.serve(i, *_batch(i))
    fc.flush()
    victims = [t for t, h in fc.tenants().items() if h == "host-0"][:2]
    before = fc.tenant_digests()
    inbox = fc._hosts["host-1"].inbox_dir

    def tear(stage):
        if stage == "transfer":
            gen = SnapshotStore(inbox).generations()[-1]
            path = SnapshotStore(inbox).path_for(gen)
            size = os.path.getsize(path)
            with open(path, "r+b") as fh:
                fh.truncate(size // 2)

    with pytest.raises(MigrationAborted):
        fc.migrate(victims, "host-1", _stage_hook=tear)
    assert fc.tenant_digests() == before
    for tid in victims:
        assert fc.tenants()[tid] == "host-0"
        assert _roster_count(fc, tid) == 1
    assert not SnapshotStore(inbox).generations()
    fc.close()


def test_migration_kill_after_cutover_is_post_commit(tmp_path):
    """The cutover hook fires AFTER the commit point: a kill there leaves the
    destination owning every tenant exactly once (the migration is final)."""
    fc = _fleet(tmp_path, hosts=2)
    for i in range(8):
        fc.serve(i, *_batch(i))
    fc.flush()
    victims = [t for t, h in fc.tenants().items() if h == "host-0"][:2]
    before = fc.tenant_digests()

    def hook(stage):
        if stage == "cutover":
            raise _Boom("killed after commit")

    with pytest.raises(_Boom):
        fc.migrate(victims, "host-1", _stage_hook=hook)
    after = fc.tenant_digests()
    for tid in victims:
        assert fc.tenants()[tid] == "host-1"
        assert after[tid] == before[tid]
        assert _roster_count(fc, tid) == 1
    fc.close()


def test_migration_guard_rails(tmp_path):
    fc = _fleet(tmp_path, hosts=2)
    fc.serve(0, *_batch(0))
    with pytest.raises(TorchMetricsUserError):
        fc.migrate([999], "host-1")  # unknown tenant
    fc.kill_host("host-1")
    with pytest.raises(TorchMetricsUserError):
        fc.migrate([0], "host-1")  # dead destination
    fc.close()


# ------------------------------------------------------------------ failover


def test_failover_bitwise_parity_and_rpo_zero(tmp_path):
    """Lease expiry → survivors adopt from snapshot + journal tail, bitwise,
    with RPO 0 at fsync-per-record; parked suspicion-window traffic replays
    to the adopter in order."""
    clock = {"t": 0.0}
    fc = _fleet(tmp_path, hosts=3, clock=lambda: clock["t"],
                lease=LeaseConfig(suspect_after=2.0, dead_after=5.0))
    for i in range(18):
        fc.serve(i % 9, *_batch(i))
    fc.flush()
    fc.snapshot_all()
    for i in range(18, 27):
        fc.serve(i % 9, *_batch(i))  # post-snapshot tail lives in the journal
    fc.flush()
    pre = fc.tenant_digests()
    victim_tenants = {t for t, h in fc.tenants().items() if h == "host-1"}
    assert victim_tenants
    fc.kill_host("host-1")
    # suspicion-window traffic for the dead host parks, nothing is dropped
    parked_tid = sorted(victim_tenants)[0]
    assert fc.serve(parked_tid, *_batch(777))
    assert fc.stats["parked"] == 1
    assert _expire(fc, clock) == ["host-1"]
    assert fc.stats["failovers"] == 1
    assert fc.stats["rpo_records"] == 0  # fsync_every=1: the journal is whole
    assert fc.stats["replayed_parked"] == 1
    assert "host-1" not in fc.hosts()
    post = fc.tenant_digests()
    for tid in pre:
        if tid == parked_tid:
            continue  # absorbed one extra (parked) batch by design
        assert post[tid] == pre[tid], f"tenant {tid} not bitwise after adoption"
        assert _roster_count(fc, tid) == 1
    # the parked tenant folded the extra batch exactly once
    ref = ServingEngine(_metric(), dataclasses.replace(_serving(), journal=None))
    for i in range(27):
        if i % 9 == parked_tid:
            ref.update(parked_tid, *_batch(i))
    ref.update(parked_tid, *_batch(777))
    ref.flush()
    assert post[parked_tid] == tenant_state_digest(ref, parked_tid)
    ref.close()
    fc.close()


def test_failover_rejoin_no_double_count(tmp_path):
    """After expiry + adoption the dead host can rejoin (epoch bump) and the
    fleet still matches the uninterrupted reference — no tenant folded
    anything twice across kill, adoption, and rejoin."""
    clock = {"t": 0.0}
    fc = _fleet(tmp_path, hosts=2, clock=lambda: clock["t"],
                lease=LeaseConfig(suspect_after=2.0, dead_after=5.0))
    log = []
    for i in range(12):
        fc.serve(i % 6, *_batch(i))
        log.append((i % 6, i))
    fc.flush()
    fc.snapshot_all()
    fc.kill_host("host-1")
    assert _expire(fc, clock) == ["host-1"]
    fc.add_host("host-1")  # rejoin: a NEW incarnation of the same id
    assert fc.membership.members()["host-1"].epoch == 2
    for i in range(12, 24):
        fc.serve(i % 6, *_batch(i))
        log.append((i % 6, i))
    fleet_digests = fc.tenant_digests()
    ref = ServingEngine(_metric(), dataclasses.replace(_serving(), journal=None))
    for tid, i in log:
        ref.update(tid, *_batch(i))
    ref.flush()
    for tid in set(t for t, _ in log):
        assert fleet_digests[tid] == tenant_state_digest(ref, tid)
    ref.close()
    fc.close()


def test_failover_replaces_stateless_suspicion_window_tenant(tmp_path):
    """A tenant FIRST seen while its rendezvous owner is down has no durable
    state to adopt — failover must re-place it (not KeyError, not lose it)
    and the parked batches must fold on the new owner."""
    clock = {"t": 0.0}
    fc = _fleet(tmp_path, hosts=2, clock=lambda: clock["t"],
                lease=LeaseConfig(suspect_after=2.0, dead_after=5.0))
    fc.kill_host("host-1")
    # find a tenant whose rendezvous seat is the dead host
    fresh = next(t for t in range(1000) if fc.owner(t) == "host-1")
    assert fc.serve(fresh, *_batch(0))  # parks: owner dead, lease unexpired
    assert _expire(fc, clock) == ["host-1"]
    assert fc.tenants()[fresh] == "host-0"  # re-placed among survivors
    fc.flush()
    ref = ServingEngine(_metric(), dataclasses.replace(_serving(), journal=None))
    ref.update(fresh, *_batch(0))
    ref.flush()
    assert fc.tenant_digests()[fresh] == tenant_state_digest(ref, fresh)
    ref.close()
    fc.close()


# ----------------------------------------------- bounded retention satellite


def test_snapshot_prune_keeps_newest(tmp_path):
    engine = ServingEngine(_metric(), _serving())
    store_dir = str(tmp_path / "snaps")
    for i in range(4):
        engine.update(0, *_batch(i))
        engine.flush()
        engine.snapshot(store_dir)
    store = SnapshotStore(store_dir)
    gens = store.generations()
    assert len(gens) == 4
    doomed = store.prune(keep_last=2)
    assert doomed == gens[:2]
    assert store.generations() == gens[2:]
    # the newest generation is untouchable and still loads
    store.prune(keep_last=1)
    assert store.generations() == [gens[-1]]
    meta, _ = store.read(gens[-1])
    assert meta["applied_seq"] >= 0 or True  # loadable is the assertion
    with pytest.raises(TorchMetricsUserError):
        store.prune(keep_last=0)
    engine.close()


def test_pruned_store_still_restores_and_replays_to_parity(tmp_path):
    """retain_snapshots=1 prunes old generations AND the journal segments
    they cover — and the survivor recipe (newest snapshot + remaining
    journal) still reconstructs the pre-crash state bitwise."""
    cfg = _serving(
        journal=str(tmp_path / "journal"),
        journal_segment_records=4,  # force rotations so pruning has prey
        retain_snapshots=1,
    )
    engine = ServingEngine(_metric(), cfg)
    retained = {}
    snap_dir = str(tmp_path / "snaps")
    for i in range(24):
        engine.update(i % 5, *_batch(i))
        engine.flush()
        retained[engine._applied_seq] = ((_batch(i)), {})
        if i % 6 == 5:
            info = engine.snapshot(snap_dir)
    assert SnapshotStore(snap_dir).generations() and len(
        SnapshotStore(snap_dir).generations()
    ) == 1  # keep_last=1 held
    assert info.get("pruned_generations", 0) >= 1
    seg_files = [f for f in os.listdir(tmp_path / "journal") if f.endswith(".tmj")]
    assert len(seg_files) < 24 // 4 + 1, "covered journal segments were not pruned"
    # more traffic past the last snapshot, then crash
    for i in range(24, 30):
        engine.update(i % 5, *_batch(i))
        engine.flush()
        retained[engine._applied_seq] = ((_batch(i)), {})
    pre = {tid: tenant_state_digest(engine, tid) for tid in engine.tenants()}
    engine._journal.crash()
    # standby: newest snapshot + surviving journal tail
    standby = ServingEngine(_metric(), dataclasses.replace(cfg, journal=None))
    standby.restore(snap_dir)
    from torchmetrics_tpu.serving import TrafficJournal

    records = TrafficJournal.read(str(tmp_path / "journal"))
    standby.replay_journal(records, lambda r: retained[r.seq])
    standby.flush()
    for tid, digest in pre.items():
        assert tenant_state_digest(standby, tid) == digest
    standby.close()


# ---------------------------------------------------------------- fleet soak


def _soak_config(root, steps=30, faults=None):
    return SoakConfig(
        traffic=TrafficConfig(steps=steps, tenants=10, seed=7),
        faults=faults,
        capacity=12,
        megabatch_size=4,
        spill_codec="none",
        durability_dir=str(root),
        snapshot_every=6,
        journal_fsync_every=1,
        fleet_hosts=3,
    )


def test_fleet_soak_parity_determinism_and_ledger(tmp_path):
    faults = FaultSchedule([
        FaultSpec(step=8, kind="host_loss", target="host-1"),
        FaultSpec(step=16, kind="host_join"),
    ])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        first = run_soak(_soak_config(tmp_path / "a", faults=faults))
        second = run_soak(_soak_config(tmp_path / "b", faults=faults))
    c = first.counters
    assert c["fleet_failover_parity"] == 1.0
    assert c["migration_parity"] == 1.0
    assert c["double_counted_batches"] == 0
    assert c["failover_rpo_records"] == 0  # fsync_every=1
    assert c["unrecovered_faults"] == 0
    assert c["host_failovers"] == 1 and c["lease_expiries"] == 1
    assert {r["kind"]: r["outcome"] for r in first.faults} == {
        "host_loss": "recovered", "host_join": "recovered",
    }
    # the determinism contract: entire counter block byte-identical, and the
    # combined per-tenant digest too
    assert first.counters == second.counters
    assert first.config["state_digest"] == second.config["state_digest"]
    assert "migration_us" in first.timing  # wall-clock lives OUTSIDE counters


def test_fleet_soak_guard_rails(tmp_path):
    # host faults outside fleet mode are refused, not silently ignored
    with pytest.raises(TorchMetricsUserError, match="fleet"):
        run_soak(SoakConfig(
            traffic=TrafficConfig(steps=12, tenants=4, seed=1),
            faults=FaultSchedule([FaultSpec(step=2, kind="host_loss", target="host-0")]),
        ))
    # fleet mode arms ONLY host faults
    with pytest.raises(TorchMetricsUserError, match="host_loss/host_join"):
        run_soak(dataclasses.replace(
            _soak_config(tmp_path),
            faults=FaultSchedule([FaultSpec(step=2, kind="gather_flaky")]),
        ))
    # a fleet of one cannot fail over
    with pytest.raises(ValueError, match="fleet_hosts"):
        SoakConfig(fleet_hosts=1, durability_dir=str(tmp_path))
    with pytest.raises(ValueError, match="durability_dir"):
        SoakConfig(fleet_hosts=3)
