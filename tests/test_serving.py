"""Multi-tenant serving engine tests (torchmetrics_tpu/serving).

The load-bearing claims, each pinned:

- **tenant isolation**: N tenants interleaved through the stacked/vmapped
  megabatch plane produce bitwise-identical integer states (and allclose
  float values) to N independently-updated reference metrics — across
  update/compute/reset, eviction + readmission round-trips, and checkpoint
  restore;
- **one compile, many tenants**: the compile counters show exactly ONE fresh
  XLA compile per (shape-class × tag) regardless of tenant count, and
  ``serve_tenant_rows``/``tenants_per_dispatch`` reconcile exactly;
- **self-warming boot**: with ``ServingConfig(aot_cache_dir=...)`` the first
  boot writes through (``write_on_miss``) and the SECOND boot serves its
  first megabatch from a cache load (zero compiles, ``aot_cache_hits == 1``);
- **fault isolation**: a poisoned megabatch quarantines only the offending
  tenant — the stack rolls back, healthy tenants keep bitwise parity.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchmetrics_tpu import aot, observability as obs
from torchmetrics_tpu.aggregation import MaxMetric, MeanMetric
from torchmetrics_tpu.classification import MulticlassAccuracy
from torchmetrics_tpu.metric import TENANT_COUNT_KEY
from torchmetrics_tpu.serving import ServingConfig, ServingEngine
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

pytestmark = pytest.mark.serving

NUM_CLASSES = 3
BATCH = 4


def _acc():
    return MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)


def _batches(rng, n, batch=BATCH):
    return [
        (jnp.asarray(rng.normal(size=(batch, NUM_CLASSES)).astype(np.float32)),
         jnp.asarray(rng.integers(0, NUM_CLASSES, batch, dtype=np.int32)))
        for _ in range(n)
    ]


def _assert_state_parity(engine, tenant_id, ref):
    """Engine slice vs reference metric state: bitwise for integer states,
    allclose for float."""
    t = engine._tenants[tenant_id]
    state = engine._tenant_state(t)
    for name, ref_v in ref._state.items():
        got = np.asarray(state[name])
        want = np.asarray(ref_v)
        if np.issubdtype(want.dtype, np.integer) or np.issubdtype(want.dtype, np.bool_):
            np.testing.assert_array_equal(got, want, err_msg=f"{tenant_id}/{name}")
        else:
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6, err_msg=f"{tenant_id}/{name}")


# ------------------------------------------------------------------- basics


def test_single_tenant_matches_reference_with_padding():
    """One tenant in a megabatch of 8 → 7 scratch pad rows; values and the
    integer states must still match the plain stateful metric exactly."""
    rng = np.random.default_rng(0)
    engine = ServingEngine(_acc(), ServingConfig(capacity=8, megabatch_size=8))
    ref = _acc()
    for preds, target in _batches(rng, 3):
        engine.update("only", preds, target)
        ref.update(preds, target)
    engine.flush()
    assert engine.stats["padded_rows"] > 0
    _assert_state_parity(engine, "only", ref)
    assert abs(float(engine.compute("only")) - float(ref.compute())) < 1e-6
    assert engine.update_count("only") == 3


def test_tenant_isolation_fuzz():
    """N tenants, shuffled interleaved traffic, repeated flushes, a mid-run
    reset — every tenant stays bitwise-isolated from every other."""
    rng = np.random.default_rng(1)
    n_tenants, rounds = 9, 3
    engine = ServingEngine(_acc(), ServingConfig(capacity=16, megabatch_size=4))
    refs = {t: _acc() for t in range(n_tenants)}
    per_tenant = {t: _batches(rng, rounds) for t in range(n_tenants)}
    order = [(t, i) for t in range(n_tenants) for i in range(rounds)]
    rng.shuffle(order)
    for step, (t, i) in enumerate(order):
        preds, target = per_tenant[t][i]
        engine.update(t, preds, target)
        refs[t].update(preds, target)
        if step == len(order) // 2:
            engine.flush()
            engine.reset(4)
            refs[4] = _acc()
    engine.flush()
    for t in range(n_tenants):
        _assert_state_parity(engine, t, refs[t])
        assert abs(float(engine.compute(t)) - float(refs[t].compute())) < 1e-6


def test_mean_metric_per_tenant_running_mean():
    """'mean'-reduced states weight by the PER-ROW update count inside the
    stack — tenants with different update depths must not cross-contaminate."""
    rng = np.random.default_rng(2)
    engine = ServingEngine(MeanMetric(), ServingConfig(capacity=8, megabatch_size=3))
    refs = {t: MeanMetric() for t in range(5)}
    for t in range(5):
        for _ in range(t + 1):  # tenant t gets t+1 updates
            v = jnp.asarray(rng.normal(size=(4,)).astype(np.float32))
            engine.update(t, v)
            refs[t].update(v)
    engine.flush()
    for t in range(5):
        np.testing.assert_allclose(
            float(engine.compute(t)), float(refs[t].compute()), rtol=1e-5
        )


def test_kwargs_traffic_and_structure_distinct_classes():
    """Keyword batches ride the vmapped fold (stacked as a kwargs pytree);
    kwargs-vs-positional traffic is a different calling convention and must
    land in a DIFFERENT shape-class (same leaves, different treedef)."""
    rng = np.random.default_rng(17)
    engine = ServingEngine(MeanMetric(), ServingConfig(capacity=8, megabatch_size=3))
    refs = {t: MeanMetric() for t in range(4)}
    for _ in range(3):
        for t in range(4):
            v = rng.normal(size=(5,)).astype(np.float32)
            w = rng.uniform(0.5, 2.0, size=(5,)).astype(np.float32)
            engine.update(t, v, weight=w)
            refs[t].update(v, weight=w)
    engine.flush()
    for t in range(4):
        np.testing.assert_allclose(float(engine.compute(t)), float(refs[t].compute()), rtol=1e-5)
    engine.update("positional", rng.normal(size=(5,)).astype(np.float32))
    engine.flush()
    assert len(engine._classes) == 2


def test_nonzero_default_states_survive_stacking():
    """MinMetric/MaxMetric defaults are ±inf — the stack must tile the real
    default, not zeros, or the first megabatch folds against garbage."""
    rng = np.random.default_rng(3)
    engine = ServingEngine(MaxMetric(), ServingConfig(capacity=4, megabatch_size=2))
    ref = MaxMetric()
    v = jnp.asarray(rng.normal(size=(6,)).astype(np.float32) - 10.0)  # all negative
    engine.update("a", v)
    ref.update(v)
    engine.flush()
    np.testing.assert_allclose(float(engine.compute("a")), float(ref.compute()), rtol=1e-6)


def test_concat_state_metric_rejected():
    from torchmetrics_tpu.aggregation import CatMetric

    with pytest.raises(TorchMetricsUserError, match="concat states"):
        ServingEngine(CatMetric())


def test_config_validation():
    with pytest.raises(ValueError, match="on_error"):
        ServingConfig(on_error="explode")
    with pytest.raises(ValueError, match="capacity"):
        ServingConfig(capacity=0)
    # a chunk wider than the stack could never be seated — rejected up front
    with pytest.raises(ValueError, match="megabatch_size"):
        ServingConfig(capacity=4, megabatch_size=8)


@pytest.mark.parametrize("on_error", ["raise", "quarantine"])
def test_full_width_megabatch_never_evicts_its_own_members(on_error):
    """Regression: capacity == megabatch_size with an over-subscribed fleet.
    Seating the chunk's later members used to evict its EARLIER members (the
    oldest-touched tenants are exactly the chunk front), crashing in 'raise'
    mode and falsely quarantining healthy tenants in 'quarantine' mode —
    megabatch members are now pinned against each other during admission."""
    rng = np.random.default_rng(18)
    engine = ServingEngine(
        _acc(), ServingConfig(capacity=4, megabatch_size=4, on_error=on_error, auto_flush=False)
    )
    refs = {t: _acc() for t in range(8)}
    batch = _batches(rng, 1)[0]
    for t in range(8):  # ingest evicts earlier tenants: chunk 1's members are all spilled
        engine.update(t, *batch)
        refs[t].update(*batch)
    engine.flush()
    roster = engine.tenants()
    assert not any(r["quarantined"] for r in roster.values()), roster
    for t in range(8):
        _assert_state_parity(engine, t, refs[t])


# --------------------------------------------------------- spill / readmission


def test_eviction_readmission_roundtrip_parity():
    """Capacity 3, fleet of 8, churned in shuffled order: every touch past
    capacity spills the LRU tenant to host and readmits on return — states
    stay bitwise-correct through arbitrarily many round-trips."""
    rng = np.random.default_rng(4)
    engine = ServingEngine(_acc(), ServingConfig(capacity=3, megabatch_size=2))
    refs = {t: _acc() for t in range(8)}
    per_tenant = {t: _batches(rng, 4) for t in range(8)}
    order = [(t, i) for t in range(8) for i in range(4)]
    rng.shuffle(order)
    for t, i in order:
        preds, target = per_tenant[t][i]
        engine.update(t, preds, target)
        refs[t].update(preds, target)
    engine.flush()
    assert engine.stats["spills"] > 0 and engine.stats["readmissions"] > 0
    for t in range(8):
        _assert_state_parity(engine, t, refs[t])
    summ = engine.summary()
    assert summ["tenant_spill_us"] > 0
    mem = engine.memory()
    assert mem["spilled_tenants"] == len([t for t in engine.tenants().values() if t["spilled"]])


def test_spilled_tenant_computes_without_readmission():
    rng = np.random.default_rng(5)
    engine = ServingEngine(_acc(), ServingConfig(capacity=4, megabatch_size=2))
    ref = _acc()
    for preds, target in _batches(rng, 2):
        engine.update("cold", preds, target)
        ref.update(preds, target)
    engine.flush()
    engine.evict("cold")
    readmissions_before = engine.stats["readmissions"]
    assert engine.tenants()["cold"]["spilled"]
    assert abs(float(engine.compute("cold")) - float(ref.compute())) < 1e-6
    # a read is not traffic: no slot churn
    assert engine.tenants()["cold"]["spilled"]
    assert engine.stats["readmissions"] == readmissions_before


def test_spill_disabled_raises_at_capacity():
    rng = np.random.default_rng(6)
    engine = ServingEngine(_acc(), ServingConfig(capacity=2, megabatch_size=2, spill=False))
    (preds, target), = _batches(rng, 1)
    engine.update("a", preds, target)
    engine.update("b", preds, target)
    with pytest.raises(TorchMetricsUserError, match="full"):
        engine.update("c", preds, target)


def test_spill_telemetry_counters():
    rng = np.random.default_rng(7)
    with obs.telemetry_session() as rec:
        engine = ServingEngine(_acc(), ServingConfig(capacity=2, megabatch_size=2))
        for t in range(4):
            for preds, target in _batches(rng, 2):
                engine.update(t, preds, target)
        engine.flush()
    c = rec.counters.snapshot().counts
    assert c["tenant_spills"] == engine.stats["spills"] > 0
    assert c["tenant_readmits"] == engine.stats["readmissions"]
    assert c["tenant_spill_us"] > 0
    assert rec.events_of("tenant_spill")


# ------------------------------------------------- one compile, many tenants


def test_one_compile_many_tenants_counters_reconcile():
    """The acceptance proof: 40 tenants, multiple flushes — exactly one fresh
    compile on the vupdate key, and the serving counters reconcile exactly
    (tenant rows == total updates; dispatch identity holds)."""
    rng = np.random.default_rng(8)
    batches = _batches(rng, 2)
    with obs.telemetry_session() as rec:
        engine = ServingEngine(_acc(), ServingConfig(capacity=64, megabatch_size=8))
        for preds, target in batches:
            for t in range(40):
                engine.update(t, preds, target)
            engine.flush()
    snap = rec.counters.snapshot()
    vkeys = {k: v for k, v in snap.per_key.items() if k.endswith(".vupdate")}
    assert len(vkeys) == 1
    (rec_row,) = vkeys.values()
    assert rec_row["compiles"] == 1  # ONE compile serves all 40 tenants
    c = snap.counts
    assert c["serve_tenant_rows"] == 80 == engine.stats["tenant_rows"]
    assert c["serve_dispatches"] == engine.stats["dispatches"] == c["dispatches"]
    assert c["jit_compiles"] + c["jit_cache_hits"] + c["aot_cache_hits"] == c["dispatches"]
    brief = snap.summary(brief=True)
    assert brief["tenants_per_dispatch"] == pytest.approx(80 / c["serve_dispatches"])
    assert rec.events_of("serve")


def test_shape_class_bucketing():
    """Two batch shapes → two stacks, two compiles (one each), full parity;
    a tenant switching shapes mid-stream is rejected with guidance."""
    rng = np.random.default_rng(9)
    small = _batches(rng, 1, batch=4)[0]
    big = _batches(rng, 1, batch=6)[0]
    with obs.telemetry_session() as rec:
        engine = ServingEngine(_acc(), ServingConfig(capacity=8, megabatch_size=2))
        ref_a, ref_b = _acc(), _acc()
        engine.update("a", *small); ref_a.update(*small)
        engine.update("b", *big); ref_b.update(*big)
        engine.flush()
    snap = rec.counters.snapshot()
    (rec_row,) = [v for k, v in snap.per_key.items() if k.endswith(".vupdate")]
    assert rec_row["compiles"] == 2  # one per shape-class
    assert len(engine._classes) == 2
    _assert_state_parity(engine, "a", ref_a)
    _assert_state_parity(engine, "b", ref_b)
    with pytest.raises(TorchMetricsUserError, match="shape-class"):
        engine.update("a", *big)


# ---------------------------------------------------------- fault isolation


def test_fault_injected_megabatch_quarantines_only_offender():
    rng = np.random.default_rng(10)
    engine = ServingEngine(
        _acc(), ServingConfig(capacity=16, megabatch_size=4, on_error="quarantine", auto_flush=False)
    )
    refs = {t: _acc() for t in range(8)}
    bad = {3}

    def hook(tenant_ids):
        if any(t in bad for t in tenant_ids):
            raise RuntimeError("injected tenant fault")

    engine._fault_hook = hook
    batch = _batches(rng, 1)[0]
    for t in range(8):
        engine.update(t, *batch)
        if t not in bad:
            refs[t].update(*batch)
    engine.flush()
    roster = engine.tenants()
    assert roster[3]["quarantined"] and engine.stats["quarantined"] == 1
    assert all(not roster[t]["quarantined"] for t in range(8) if t != 3)
    for t in range(8):
        if t in bad:
            continue
        _assert_state_parity(engine, t, refs[t])
    # quarantined tenant rejects traffic until reset lifts it
    with pytest.raises(TorchMetricsUserError, match="quarantined"):
        engine.update(3, *batch)
    engine.reset(3)
    engine._fault_hook = None
    engine.update(3, *batch)
    engine.flush()
    ref3 = _acc()
    ref3.update(*batch)
    _assert_state_parity(engine, 3, ref3)


def test_spilled_offender_quarantine_keeps_codec_peers_bitwise():
    """Fault-injected megabatch whose members got evicted (codec-spilled)
    AFTER enqueue: with twice as many distinct queued tenants as slots, the
    flush must re-seat each chunk INSIDE ``_dispatch_rows`` — readmissions
    decode int8-spilled rows and evictions spill pending residents mid-flush.
    When that dispatch then fails, the quarantine rollback must restore the
    seating bookkeeping along with the stack; otherwise the re-drives fold
    healthy tenants' batches onto the rolled-back victims' rows. Only the
    pinned offender may quarantine, and every surviving peer's state must
    equal its reference bitwise (int states cross the int8 codec raw)."""
    rng = np.random.default_rng(12)
    engine = ServingEngine(
        _acc(),
        ServingConfig(
            capacity=4,
            megabatch_size=4,
            on_error="quarantine",
            auto_flush=False,
            spill_codec="int8",
        ),
    )
    refs = {t: _acc() for t in range(8)}
    # per-tenant DISTINCT batches: if a rollback leaves a tenant pointed at
    # another tenant's rows, the folded values diverge and parity catches it
    first = _batches(rng, 8)
    second = _batches(rng, 8)

    # seat 0-3, then push them out with 4-7: 0-3 now live int8-encoded on host
    for t in range(4):
        engine.update(t, *first[t])
        refs[t].update(*first[t])
    engine.flush()
    for t in range(4, 8):
        engine.update(t, *first[t])
        refs[t].update(*first[t])
    engine.flush()
    assert engine.stats["spills"] >= 4
    assert all(engine._tenants[t].spilled is not None for t in range(4))

    # queue a second round for ALL EIGHT tenants before flushing: enqueue-time
    # admission churns the four slots end to end, so by flush time tenants 0-3
    # are spilled AGAIN (still holding only their first-round states) and the
    # flush itself must readmit them inside the faulted dispatch
    def hook(tenant_ids):
        if 0 in tenant_ids:
            raise RuntimeError("injected fault pinned to spilled tenant 0")

    engine._fault_hook = hook
    for t in range(8):
        engine.update(t, *second[t])
        if t != 0:
            refs[t].update(*second[t])
    assert all(engine._tenants[t].spilled is not None for t in range(4))
    engine.flush()

    roster = engine.tenants()
    assert roster[0]["quarantined"] and engine.stats["quarantined"] == 1
    assert all(not roster[t]["quarantined"] for t in range(1, 8))
    for t in range(1, 8):
        _assert_state_parity(engine, t, refs[t])
    # reset lifts the quarantine and the tenant serves again from a clean row
    engine.reset(0)
    engine._fault_hook = None
    engine.update(0, *second[0])
    engine.flush()
    ref0 = _acc()
    ref0.update(*second[0])
    _assert_state_parity(engine, 0, ref0)


def test_quarantine_emits_telemetry():
    rng = np.random.default_rng(11)
    batch = _batches(rng, 1)[0]
    with obs.telemetry_session() as rec:
        engine = ServingEngine(
            _acc(), ServingConfig(capacity=8, megabatch_size=2, on_error="quarantine", auto_flush=False)
        )
        engine._fault_hook = lambda tids: (_ for _ in ()).throw(RuntimeError("boom"))
        engine.update("x", *batch)
        engine.flush()
    assert rec.counters.snapshot().counts["quarantines"] == 1
    assert rec.events_of("quarantine")


# ----------------------------------------------------- checkpoint round-trips


def test_checkpoint_roundtrips_with_standalone_metric():
    rng = np.random.default_rng(12)
    engine = ServingEngine(_acc(), ServingConfig(capacity=4, megabatch_size=2))
    ref = _acc()
    for preds, target in _batches(rng, 3):
        engine.update("ckpt", preds, target)
        ref.update(preds, target)
    engine.flush()
    sd = engine.state_dict("ckpt")
    assert sd["_update_count"] == 3
    # engine checkpoint → standalone metric
    solo = _acc()
    solo.load_state_dict(sd)
    np.testing.assert_allclose(float(solo.compute()), float(ref.compute()), rtol=1e-6)
    # standalone metric checkpoint → fresh engine tenant (restores as spilled,
    # readmits on next traffic)
    ref.persistent(True)
    engine2 = ServingEngine(_acc(), ServingConfig(capacity=4, megabatch_size=2))
    engine2.load_state_dict("restored", ref.state_dict())
    extra = _batches(rng, 1)[0]
    engine2.update("restored", *extra)
    engine2.flush()
    ref.update(*extra)
    _assert_state_parity(engine2, "restored", ref)


def test_load_state_dict_validates_keys():
    engine = ServingEngine(_acc(), ServingConfig(capacity=2, megabatch_size=2))
    with pytest.raises(TorchMetricsUserError, match="missing"):
        engine.load_state_dict("t", {"tp": np.zeros(NUM_CLASSES, np.int32)})
    with pytest.raises(TorchMetricsUserError, match="unknown"):
        engine.load_state_dict("t", {
            **{k: np.zeros(NUM_CLASSES, np.int32) for k in ("tp", "fp", "tn", "fn")},
            "bogus": np.zeros(3),
        })


# ------------------------------------------------------- self-warming (aot)


@pytest.mark.aot
def test_write_on_miss_second_boot_is_warm(tmp_path):
    """Boot 1: miss → compile → write-through. Boot 2 (fresh engine, fresh
    template, same cache dir): the megabatch program LOADS — zero fresh
    compiles, aot_cache_hits == 1, identical values."""
    cache = str(tmp_path / "serve-aot")
    rng = np.random.default_rng(13)
    batch = _batches(rng, 1)[0]
    cfg = lambda: ServingConfig(capacity=8, megabatch_size=4, aot_cache_dir=cache)

    e1 = ServingEngine(_acc(), cfg())
    for t in range(4):
        e1.update(t, *batch)
    e1.flush()
    plane1 = aot.active_plane()
    assert plane1.stats["writes"] >= 1 and plane1.stats["misses"] >= 1
    v1 = float(e1.compute(0))
    aot.disable()

    with obs.telemetry_session() as rec:
        e2 = ServingEngine(_acc(), cfg())
        for t in range(4):
            e2.update(t, *batch)
        e2.flush()
        v2 = float(e2.compute(0))
    plane2 = aot.active_plane()
    assert plane2.stats["loads"] == 1 and plane2.stats["misses"] == 0
    snap = rec.counters.snapshot()
    (rec_row,) = [v for k, v in snap.per_key.items() if k.endswith(".vupdate")]
    assert rec_row["compiles"] == 0 and rec_row["aot_hits"] == 1
    c = snap.counts
    assert c["aot_cache_hits"] == 1
    assert c["jit_compiles"] + c["jit_cache_hits"] + c["aot_cache_hits"] == c["dispatches"]
    assert v1 == v2
    aot.disable()


@pytest.mark.aot
def test_engine_precompile_and_prefetch(tmp_path):
    """Deploy-time warm start: precompile publishes the megabatch program for
    an example shape-class; a fresh boot prefetches it and serves its first
    real megabatch without compiling."""
    cache = str(tmp_path / "precompile-aot")
    rng = np.random.default_rng(14)
    batch = _batches(rng, 1)[0]
    aot.enable(cache)
    e1 = ServingEngine(_acc(), ServingConfig(capacity=8, megabatch_size=4))
    report = e1.precompile(*batch)
    (row,) = report.values()
    assert row["status"] == "written"
    assert e1.precompile(*batch)[list(report)[0]]["status"] == "cached"
    aot.disable()

    aot.enable(cache)
    e2 = ServingEngine(_acc(), ServingConfig(capacity=8, megabatch_size=4))
    (pref,) = e2.prefetch(*batch).values()
    assert pref["status"] == "loaded"
    with obs.telemetry_session() as rec:
        e2.update("t", *batch)
        e2.flush()
    snap = rec.counters.snapshot()
    (rec_row,) = [v for k, v in snap.per_key.items() if k.endswith(".vupdate")]
    assert rec_row["compiles"] == 0 and rec_row["aot_hits"] == 1
    aot.disable()


# ------------------------------------------------------- placement / sharding


def test_shard_by_tenant_placement():
    """Stacks placed with parallel.tenant_sharding spread tenant rows over
    the 8-device CPU mesh; parity is unchanged. capacity=15 → 16 rows, evenly
    divisible by the mesh axis."""
    from torchmetrics_tpu.parallel import tenant_sharding

    mesh = jax.make_mesh((8,), ("tenants",), devices=jax.devices()[:8])
    sharding = tenant_sharding(mesh)
    rng = np.random.default_rng(15)
    engine = ServingEngine(
        _acc(), ServingConfig(capacity=15, megabatch_size=4, sharding=sharding)
    )
    refs = {t: _acc() for t in range(6)}
    for preds, target in _batches(rng, 2):
        for t in range(6):
            engine.update(t, preds, target)
            refs[t].update(preds, target)
    engine.flush()
    for t in range(6):
        _assert_state_parity(engine, t, refs[t])
    cls = next(iter(engine._classes.values()))
    assert cls.stacked[TENANT_COUNT_KEY].shape == (16,)


def test_tenant_sharding_unknown_axis_raises():
    from torchmetrics_tpu.parallel import tenant_sharding

    mesh = jax.make_mesh((8,), ("dp",), devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="no axis"):
        tenant_sharding(mesh)


# ------------------------------------------------------------- misc plumbing


def test_template_is_not_disturbed():
    rng = np.random.default_rng(16)
    template = _acc()
    engine = ServingEngine(template, ServingConfig(capacity=4, megabatch_size=2))
    batch = _batches(rng, 1)[0]
    engine.update("t", *batch)
    engine.flush()
    assert template.update_count == 0
    assert all(int(np.asarray(v).sum()) == 0 for v in template._state.values())


def test_counters_fleet_vector_includes_serving_fields():
    """The new serve_*/tenant_* fields ride the fleet counter vector and
    aggregate by exact fieldwise sum like every other field."""
    from torchmetrics_tpu.observability import COUNTER_FIELDS, Counters, aggregate_counters

    a, b = Counters(), Counters()
    a.record_serve_dispatch(8, 2)
    a.record_tenant_spill(0.001)
    b.record_serve_dispatch(4, 0)
    b.record_tenant_spill(0.002, readmit=True)
    fleet = aggregate_counters([a.snapshot(), b.snapshot()])
    assert fleet["serve_dispatches"] == 2
    assert fleet["serve_tenant_rows"] == 12
    assert fleet["tenant_spills"] == 1 and fleet["tenant_readmits"] == 1
    assert fleet["tenant_spill_us"] == 3000
    assert len(a.counts_vector()) == len(COUNTER_FIELDS)
    assert "serve_dispatches" in COUNTER_FIELDS
