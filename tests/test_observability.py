"""Observability layer: events, counters, sinks, tracing, and the acceptance
contract — with telemetry enabled, a scripted run's counters reconcile exactly
(compiles + cache hits == dispatches, the injected retry appears as an event,
the hot loop performs zero device→host readbacks); with telemetry disabled,
the dispatch path constructs no events and does no telemetry work."""

import json
import os
import warnings

import importlib.util
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchmetrics_tpu as tm
from torchmetrics_tpu import MetricCollection, observability as obs
from torchmetrics_tpu.metric import HostMetric, Metric
from torchmetrics_tpu.reliability import (
    ReliabilityConfig,
    RetryPolicy,
    inject_dispatch_fault,
)

pytestmark = pytest.mark.telemetry

_FAST_RETRY = dict(backoff_base=0.0, jitter=0.0, sleep_fn=lambda s: None)


def _x(n=8, seed=0):
    return jnp.asarray(np.random.default_rng(seed).random(n).astype(np.float32))


class _SumState(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("s", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, x):
        return {"s": x.sum()}

    def _compute(self, state):
        return state["s"]


class _HostSum(HostMetric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("s", default=np.zeros(()), dist_reduce_fx="sum")

    def _host_batch_state(self, x):
        return {"s": jnp.asarray(np.asarray(x).sum())}

    def _compute(self, state):
        return state["s"]


# --------------------------------------------------------------- unit: counters


def test_counters_snapshot_and_diff():
    c = obs.Counters()
    assert c.record_dispatch("M#0.update", "f32(4,)") == (True, 1)
    assert c.record_dispatch("M#0.update", "f32(4,)") == (False, 1)
    assert c.record_dispatch("M#0.update", "f32(5,)") == (True, 2)
    c.record_d2h(128)
    first = c.snapshot()
    c.record_dispatch("M#0.update", "f32(6,)")
    c.record_sync(256)
    second = c.snapshot()
    assert first["dispatches"] == 3
    assert first["jit_compiles"] == 2 and first["jit_cache_hits"] == 1
    assert first["retraces"] == 1
    assert first["d2h_readbacks"] == 1 and first["d2h_bytes"] == 128
    delta = second.diff(first)
    assert delta["dispatches"] == 1 and delta["jit_compiles"] == 1
    assert delta["sync_calls"] == 1 and delta["sync_payload_bytes"] == 256
    assert delta.per_key["M#0.update"]["signatures"] == ["f32(6,)"]
    brief = second.summary(brief=True)
    assert set(brief) == {
        "dispatches", "jit_compiles", "jit_cache_hits", "retraces",
        "host_dispatches", "d2h_readbacks", "sync_calls",
        "gathers_coalesced", "collectives_per_sync",
        "serve_dispatches", "tenants_per_dispatch",
    }
    c.reset()
    assert c.snapshot()["dispatches"] == 0


# ------------------------------------------------------------------ unit: sinks


def test_ring_buffer_sink_evicts_oldest():
    sink = obs.RingBufferSink(capacity=3)
    for i in range(5):
        sink.emit(obs.TelemetryEvent(kind="dispatch", metric=f"m{i}", tag="update", timestamp=float(i)))
    assert sink.evicted == 2
    assert [e.metric for e in sink.events] == ["m2", "m3", "m4"]
    assert len(sink.of_kind("dispatch")) == 3
    assert len(sink.drain()) == 3 and sink.events == ()


def test_jsonl_sink_and_trace_report(tmp_path):
    trace = tmp_path / "trace.jsonl"
    cfg = obs.TelemetryConfig(sinks=(obs.JSONLSink(str(trace)), obs.RingBufferSink()))
    m = _SumState(reliability=ReliabilityConfig(retry=RetryPolicy(max_attempts=3, **_FAST_RETRY)))
    with obs.telemetry_session(cfg):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            with inject_dispatch_fault(m, fail_on=2, times=1, tag="update"):
                for _ in range(3):
                    m.update(_x())
        m.compute()
    lines = [json.loads(l) for l in trace.read_text().splitlines()]
    assert all("kind" in e and "timestamp" in e for e in lines)
    assert {"dispatch", "retry", "compute"} <= {e["kind"] for e in lines}

    # tools/trace_report.py renders the same file into a per-metric table
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(os.path.dirname(__file__), "..", "tools", "trace_report.py")
    )
    trace_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_report)
    report = trace_report.aggregate(trace_report.load_events(str(trace)))
    rows = {(r["metric"], r["phase"]): r for r in report["rows"]}
    update_row = rows[("_SumState#0", "update")]
    assert update_row["events"] == 3
    assert update_row["compiles"] == 1 and update_row["cache_hits"] == 2
    assert report["totals"]["retries"] == 1
    rendered = trace_report.render_table(report)
    assert "_SumState#0" in rendered and "retries: 1" in rendered


def test_jsonl_sink_flushes_on_close_and_context_exit(tmp_path):
    """Buffered sinks (flush_every > 1) may hold lines in userspace, but
    close()/context-exit must land every complete line on disk — a trace
    copied off a preempted host can't end mid-line because of OUR buffering."""
    path = tmp_path / "buffered.jsonl"
    sink = obs.JSONLSink(str(path), flush_every=100)
    for i in range(3):
        sink.emit(obs.TelemetryEvent(kind="dispatch", metric=f"m{i}", tag="update", timestamp=float(i)))
    sink.close()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [e["metric"] for e in lines] == ["m0", "m1", "m2"]
    sink.close()  # idempotent
    with obs.JSONLSink(str(path), flush_every=100) as ctx_sink:
        ctx_sink.emit(obs.TelemetryEvent(kind="compute", metric="m3", tag="compute", timestamp=4.0))
    assert json.loads(path.read_text().splitlines()[-1])["metric"] == "m3"
    with pytest.raises(ValueError, match="flush_every"):
        obs.JSONLSink(str(path), flush_every=0)
    # session teardown routes through close() too: a buffered sink attached to
    # a telemetry_session leaves a complete file after the block
    trace = tmp_path / "session.jsonl"
    m = _SumState()
    with obs.telemetry_session(obs.TelemetryConfig(sinks=(obs.JSONLSink(str(trace), flush_every=64),))):
        m.update(_x())
    # the dispatch line plus the histogram snapshot the session flushes at close
    assert {json.loads(l)["kind"] for l in trace.read_text().splitlines()} == {"dispatch", "hist"}


def test_jsonl_trace_tolerates_bad_line(tmp_path):
    """Skip-bad-line tolerance stays: a line truncated by a hard kill mid-write
    is warned about and skipped, the rest of the trace still renders."""
    trace = tmp_path / "torn.jsonl"
    with obs.JSONLSink(str(trace)) as sink:
        sink.emit(obs.TelemetryEvent(kind="dispatch", metric="m0", tag="update", timestamp=1.0))
    with open(trace, "a", encoding="utf-8") as fh:
        fh.write('{"kind": "dispatch", "metr')  # torn final line
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(os.path.dirname(__file__), "..", "tools", "trace_report.py")
    )
    trace_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_report)
    events = trace_report.load_events(str(trace))
    assert len(events) == 1 and events[0]["metric"] == "m0"


def test_callback_sink_hooks():
    seen = {"update": 0, "compute": 0, "sync": 0, "retry": 0, "quarantine": 0, "any": 0}
    cb = obs.CallbackSink(
        on_update=lambda e: seen.__setitem__("update", seen["update"] + 1),
        on_compute=lambda e: seen.__setitem__("compute", seen["compute"] + 1),
        on_sync=lambda e: seen.__setitem__("sync", seen["sync"] + 1),
        on_retry=lambda e: seen.__setitem__("retry", seen["retry"] + 1),
        on_quarantine=lambda e: seen.__setitem__("quarantine", seen["quarantine"] + 1),
        on_event=lambda e: seen.__setitem__("any", seen["any"] + 1),
    )
    pol = RetryPolicy(max_attempts=3, **_FAST_RETRY)
    m = _SumState(
        reliability=ReliabilityConfig(retry=pol, check_finite=False),
        distributed_available_fn=lambda: True,
        dist_sync_fn=lambda v, g: [v, v],
    )
    col = MetricCollection({"bomb": _SumState()}, on_error="quarantine")
    with obs.telemetry_session(obs.TelemetryConfig(sinks=(cb,))):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            with inject_dispatch_fault(m, fail_on=1, times=1, tag="update"):
                m.update(_x())
            m.compute()  # fake-distributed -> sync event too
            col.update(_x())
            with inject_dispatch_fault(col["bomb"], fail_on=1, times=5, tag="update"):
                col.update(_x())
    assert seen["update"] >= 1 and seen["compute"] == 1 and seen["sync"] == 1
    assert seen["retry"] >= 1 and seen["quarantine"] == 1
    assert seen["any"] >= sum(v for k, v in seen.items() if k != "any")


# ------------------------------------------------- acceptance: scripted run


def test_scripted_run_counters_reconcile():
    """update×K under one injected transient fault → sync → compute: compiles +
    cache hits == dispatch count, the retry shows up as an on_retry event, and
    the hot loop performs zero device→host readbacks (transfer-guard enforced)."""
    K = 6
    pol = RetryPolicy(max_attempts=3, **_FAST_RETRY)
    m = _SumState(
        reliability=ReliabilityConfig(retry=pol),
        distributed_available_fn=lambda: True,
        dist_sync_fn=lambda v, g: [v, v],
    )
    x = _x()
    with obs.telemetry_session() as rec:
        with jax.transfer_guard_device_to_host("disallow"):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", UserWarning)
                with inject_dispatch_fault(m, fail_on=3, times=1, tag="update") as hook:
                    for _ in range(K):
                        m.update(x)
        hot = rec.counters.snapshot()
        value = m.compute()
    assert hook.raised == 1
    # hot loop: every dispatch is a compile or a cache hit, nothing unaccounted
    assert hot["dispatches"] == K
    assert hot["jit_compiles"] + hot["jit_cache_hits"] == hot["dispatches"]
    assert hot["jit_compiles"] == 1 and hot["retraces"] == 0
    # the injected transient fault surfaced as exactly one retry event
    assert hot["retries"] == 1
    retry_events = rec.events_of("retry")
    assert len(retry_events) == 1 and retry_events[0].payload["attempt"] == 1
    # the hot loop performed ZERO device→host readbacks (counter + guard agree)
    assert hot["d2h_readbacks"] == 0
    # sync + compute happened after the hot loop and were recorded; the single
    # scalar leaf rode the coalesced plane (metadata + one bucket collective),
    # so the per-leaf gather counter stays at zero
    final = rec.counters.snapshot()
    assert final["sync_calls"] == 1 and final["gather_calls"] == 0
    assert final["gathers_coalesced"] == 1 and final["sync_collectives"] == 2
    assert final["sync_payload_bytes"] == 4  # one f32 scalar state
    assert final["computes"] == 1
    assert len(rec.events_of("sync")) == 1
    # telemetry never changed the math: 6 updates x sum(x), two "processes"
    assert float(value) == pytest.approx(2 * K * float(np.asarray(x).sum()), rel=1e-5)


def test_disabled_telemetry_constructs_no_events(monkeypatch):
    """With no session active the dispatch path must do NO telemetry work: no
    event objects, no signature hashing, no clock reads, no histogram
    recording, no SLO evaluation (and, established elsewhere by transfer
    guard, no D2H)."""
    def boom(*a, **k):
        raise AssertionError("telemetry work performed while disabled")

    assert not obs.enabled()
    monkeypatch.setattr(obs.events.TelemetryEvent, "__init__", boom)
    monkeypatch.setattr(obs.TelemetryRecorder, "_signature", staticmethod(boom))
    monkeypatch.setattr(obs.tracing, "monotonic", boom)
    # the health plane must be just as silent: recording a histogram sample,
    # feeding the SLO window, or evaluating a rule while disabled is a leak
    monkeypatch.setattr(obs.Histogram, "record", boom)
    monkeypatch.setattr(obs.HistogramRegistry, "record", boom)
    monkeypatch.setattr(obs.HistogramRegistry, "record_duration", boom)
    monkeypatch.setattr(obs.SloEngine, "observe", boom)
    monkeypatch.setattr(obs.SloEngine, "evaluate", boom)
    # the causal trace plane must be silent too: no span objects, no id hashing
    monkeypatch.setattr(obs.spans.SpanContext, "__init__", boom)
    monkeypatch.setattr(obs.spans, "_digest", boom)
    m = _SumState()
    m.update(_x())
    m.forward(_x())
    assert float(m.compute()) > 0
    h = _HostSum()
    h.update(_x())
    h.compute()
    # sync path too (fake distributed)
    s = _SumState(distributed_available_fn=lambda: True, dist_sync_fn=lambda v, g: [v, v])
    s.update(_x())
    s.compute()
    # retry path: a disabled session must not record backoff histograms either
    pol = RetryPolicy(max_attempts=2, **_FAST_RETRY)
    r = _SumState(reliability=ReliabilityConfig(retry=pol))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        with inject_dispatch_fault(r, fail_on=1, times=1, tag="update"):
            r.update(_x())


# ------------------------------------------------------------------ satellites


def test_retrace_sentinel_names_offending_shapes():
    m = _SumState()
    cfg = obs.TelemetryConfig(retrace_warn_threshold=2)
    with obs.telemetry_session(cfg) as rec:
        with pytest.warns(UserWarning, match=r"Retrace sentinel.*_SumState#\d+\.update"):
            for n in (4, 5, 6, 7):
                m.update(_x(n))
        # threshold crossing warns once; retrace events track every new signature
        assert len(rec.events_of("retrace")) == 3
        assert rec.counters.snapshot()["retraces"] == 3
        sigs = rec.events_of("retrace")[0].signature
        assert "float32" in sigs
    with obs.telemetry_session(cfg):
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # stable shapes: sentinel stays quiet
            m2 = _SumState()
            for _ in range(6):
                m2.update(_x(4))


def test_retry_exhausted_warns_and_emits_event():
    pol = RetryPolicy(max_attempts=2, **_FAST_RETRY)
    m = _SumState(reliability=ReliabilityConfig(retry=pol))
    with obs.telemetry_session() as rec:
        with pytest.warns(UserWarning, match="Retry budget exhausted"):
            with inject_dispatch_fault(m, fail_on=1, times=5, tag="update"):
                with pytest.raises(Exception):
                    m.update(_x())
    snap = rec.counters.snapshot()
    assert snap["retries"] == 1 and snap["retries_exhausted"] == 1
    ev = rec.events_of("retry_exhausted")
    assert len(ev) == 1
    assert ev[0].metric == "_SumState.update"
    assert ev[0].payload["attempts"] == 2


def test_quarantine_and_skip_events():
    for mode, status, counter in (("quarantine", "quarantined", "quarantines"), ("skip", "skipped", "skips")):
        col = MetricCollection({"ok": tm.SumMetric(), "bad": _SumState()}, on_error=mode)
        with obs.telemetry_session() as rec:
            col.update(_x())
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", UserWarning)
                with inject_dispatch_fault(col["bad"], fail_on=1, times=5, tag="update"):
                    col.update(_x())
        events = rec.events_of("quarantine")
        assert len(events) == 1, mode
        assert events[0].metric == "bad" and events[0].tag == "update"
        assert events[0].payload["status"] == status
        assert rec.counters.snapshot()[counter] == 1


def test_collection_telemetry_summary_fused_attribution():
    col = MetricCollection({"s1": tm.SumMetric(), "s2": tm.SumMetric()})
    with obs.telemetry_session():
        col.update(_x())  # both dispatch; groups derived after this batch
        col.update(_x())  # fused: only the leader dispatches
        summary = col.telemetry_summary()
    assert summary["enabled"]
    members = summary["members"]
    leaders = [n for n, info in members.items() if "fused_into" not in info]
    followers = [n for n, info in members.items() if "fused_into" in info]
    assert len(leaders) == 1 and len(followers) == 1
    assert members[followers[0]]["fused_into"] == leaders[0]
    assert members[leaders[0]]["dispatches"] == 2
    assert members[followers[0]]["dispatches"] == 1  # pre-fusion batch only
    assert summary["counters"]["dispatches"] == 3
    assert list(summary["compute_groups"].values()) == [[leaders[0], followers[0]]]


def test_telemetry_summary_disabled():
    col = MetricCollection({"s": tm.SumMetric()})
    assert col.telemetry_summary() == {"enabled": False}


def test_host_metric_dispatch_recorded():
    h = _HostSum()
    with obs.telemetry_session() as rec:
        h.update(_x())
        h.forward(_x())
    snap = rec.counters.snapshot()
    assert snap["host_dispatches"] == 2 and snap["dispatches"] == 0
    ev = rec.events_of("dispatch")
    assert all(e.payload.get("jitted") is False for e in ev)


def test_state_dict_d2h_counted():
    m = tm.SumMetric()
    m.persistent(True)
    m.update(_x())
    with obs.telemetry_session() as rec:
        m.state_dict()
    snap = rec.counters.snapshot()
    assert snap["d2h_readbacks"] == 1 and snap["d2h_bytes"] == 4  # f32 scalar
    assert rec.events_of("d2h")[0].tag == "state_dict"


def test_compute_on_cpu_append_d2h_counted():
    m = tm.CatMetric(compute_on_cpu=True)
    with obs.telemetry_session() as rec:
        m.update(_x(4))
        m.update(_x(4))
    snap = rec.counters.snapshot()
    assert snap["d2h_readbacks"] == 2 and snap["d2h_bytes"] == 32
    assert all(e.tag == "compute_on_cpu_append" for e in rec.events_of("d2h"))


def test_blocking_timing_mode_records_durations():
    with obs.telemetry_session(obs.TelemetryConfig(block_until_ready=True)) as rec:
        m = _SumState()
        for _ in range(3):
            m.update(_x())
        m.compute()
    spans = rec.events_of("dispatch", "compute")
    assert len(spans) == 4
    assert all(e.duration_s is not None and e.duration_s >= 0 for e in spans)


def test_fault_injected_run_events_captured():
    """Reliability + observability together: a FlakyGather sync retry and a
    dispatch-fault retry both land in one session's event stream."""
    from torchmetrics_tpu.reliability import FlakyGather

    pol = RetryPolicy(max_attempts=3, **_FAST_RETRY)
    flaky = FlakyGather(inner=lambda v, g: [v, v], fail_times=1)
    m = _SumState(
        reliability=ReliabilityConfig(retry=pol),
        distributed_available_fn=lambda: True,
        dist_sync_fn=flaky,
    )
    with obs.telemetry_session() as rec:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            with inject_dispatch_fault(m, fail_on=1, times=1, tag="update"):
                m.update(_x())
            m.compute()
    snap = rec.counters.snapshot()
    assert snap["retries"] == 2  # one dispatch retry + one sync retry
    describes = [e.metric for e in rec.events_of("retry")]
    assert "_SumState.update" in describes and "_SumState.sync" in describes
    assert snap["sync_calls"] == 2  # failed attempt + successful retry both entered process_sync
    assert flaky.failures == 1


def test_metric_identity_fresh_per_session():
    """A metric surviving its session gets a fresh id in the next one — stale
    stamps (or unpickled metrics) must never merge into an unrelated metric's
    counters."""
    survivor = _SumState()
    with obs.telemetry_session() as rec1:
        survivor.update(_x())
    with obs.telemetry_session() as rec2:
        other = _SumState()
        other.update(_x())  # claims id 0 of the new session
        survivor.update(_x())
    assert rec1.counters.snapshot()["dispatches"] == 1
    keys2 = set(rec2.counters.snapshot().per_key)
    assert keys2 == {"_SumState#0.update", "_SumState#1.update"}
    assert rec2.metric_summary(other)["dispatches"] == 1
    assert rec2.metric_summary(survivor)["dispatches"] == 1


def test_session_lifecycle_and_replacement():
    rec1 = obs.enable()
    assert obs.active() is rec1 and obs.enabled()
    rec2 = obs.enable()  # replaces (closes) rec1
    assert obs.active() is rec2
    out = obs.disable()
    assert out is rec2 and not obs.enabled()
    assert obs.disable() is None  # idempotent
