"""Round-2 regression tests for the sync planes (VERDICT weak #2/#7).

Covers: n-way "mean" folds (stacked reduction, not sequential pairwise), the
injectable ``dist_sync_fn`` process plane (plane 2), and the count-weighted
``merge_state`` chain — reference semantics at metric.py:481,525-540.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu import Metric
from torchmetrics_tpu.parallel import sync as _sync


class DummyMean(Metric):
    """A metric whose single state uses the public ``dist_reduce_fx="mean"`` contract."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("v", default=jnp.zeros(()), dist_reduce_fx="mean")

    def _batch_state(self, x):
        return {"v": jnp.asarray(x, jnp.float32).mean()}

    def _compute(self, state):
        return state["v"]


def test_fold_gathered_mean_three_ranks():
    gathered = [jnp.asarray(1.0), jnp.asarray(2.0), jnp.asarray(6.0)]
    out = _sync._fold_gathered(gathered, "mean")
    assert np.isclose(float(out), 3.0)  # ((1+2)/2+6)/2 = 3.75 would be the pairwise bug


def test_fold_gathered_all_tags():
    gathered = [jnp.asarray([1.0, 4.0]), jnp.asarray([2.0, 2.0]), jnp.asarray([6.0, 0.0])]
    assert np.allclose(np.asarray(_sync._fold_gathered(gathered, "sum")), [9.0, 6.0])
    assert np.allclose(np.asarray(_sync._fold_gathered(gathered, "mean")), [3.0, 2.0])
    assert np.allclose(np.asarray(_sync._fold_gathered(gathered, "max")), [6.0, 4.0])
    assert np.allclose(np.asarray(_sync._fold_gathered(gathered, "min")), [1.0, 0.0])
    assert np.allclose(np.asarray(_sync._fold_gathered(gathered, "cat")), [1, 4, 2, 2, 6, 0])


def test_update_running_mean_exact():
    """Sequential updates of a mean state equal the mean over all batches."""
    m = DummyMean()
    batches = [1.0, 2.0, 6.0, 11.0]
    for b in batches:
        m.update(np.asarray(b))
    assert np.isclose(float(m.compute()), np.mean(batches))


def test_forward_running_mean_exact():
    m = DummyMean()
    batches = [3.0, 5.0, 13.0]
    for b in batches:
        m(np.asarray(b))
    assert np.isclose(float(m.compute()), np.mean(batches))


def test_merge_state_mean_three_participants():
    """merge_state chains stay exact for mean states (count-weighted fold)."""
    ms = [DummyMean() for _ in range(3)]
    vals = [1.0, 2.0, 6.0]
    for m, v in zip(ms, vals):
        m.update(np.asarray(v))
    ms[0].merge_state(ms[1])
    ms[0].merge_state(ms[2])
    assert np.isclose(float(ms[0].compute()), np.mean(vals))


def test_merge_state_mean_weighted_by_update_count():
    a, b = DummyMean(), DummyMean()
    for v in (1.0, 2.0, 3.0):
        a.update(np.asarray(v))
    b.update(np.asarray(10.0))
    a.merge_state(b)
    assert np.isclose(float(a.compute()), np.mean([1.0, 2.0, 3.0, 10.0]))


def _fake_gather_factory(world_size: int):
    """dist_sync_fn stub: pretend each rank holds value + rank (reference seam
    metric.py:133) so the fold logic of plane 2 is exercised without processes."""

    def fake_gather(value, process_group=None):
        return [jnp.asarray(value) + i for i in range(world_size)]

    return fake_gather


@pytest.mark.parametrize("world", [2, 3, 4])
def test_process_sync_mean_with_fake_gather(world):
    m = DummyMean(dist_sync_fn=_fake_gather_factory(world))
    m.update(np.asarray(4.0))
    m.sync(distributed_available=lambda: True)
    # ranks hold 4, 5, ... 4+world-1 → mean = 4 + (world-1)/2
    assert np.isclose(float(m._state["v"]), 4.0 + (world - 1) / 2)
    m.unsync()
    assert np.isclose(float(m._state["v"]), 4.0)


@pytest.mark.parametrize("world", [2, 3])
def test_process_sync_sum_and_compute_restores(world):
    from tests.test_metric_base import DummySum

    m = DummySum(dist_sync_fn=_fake_gather_factory(world), distributed_available_fn=lambda: True)
    m.update(np.asarray([1.0, 2.0]))  # local sum = 3
    val = m.compute()  # sync → sum over ranks → unsync
    expect = sum(3.0 + i for i in range(world))
    assert np.isclose(float(val), expect)
    assert np.isclose(float(m._state["x"]), 3.0)  # local state restored


def test_process_sync_cat_fold():
    def fake_gather(value, process_group=None):
        return [jnp.asarray(value), jnp.asarray(value) * 10]

    out = _sync.process_sync({"x": jnp.asarray([1.0, 2.0])}, {"x": "cat"}, dist_sync_fn=fake_gather)
    assert np.allclose(np.asarray(out["x"]), [1.0, 2.0, 10.0, 20.0])


def test_weighted_mean_zero_total_keeps_left():
    out = _sync.weighted_mean(jnp.asarray(5.0), jnp.asarray(7.0), 0.0, 0.0)
    assert np.isclose(float(out), 5.0)


def test_merge_state_dict_chain_exact():
    """Dict merges fold weight 1 into the count so chains stay exact (review fix)."""
    m = DummyMean()
    m.update(np.asarray(10.0))
    m.merge_state({"v": jnp.asarray(20.0)})
    m.merge_state({"v": jnp.asarray(30.0)})
    assert np.isclose(float(m.compute()), 20.0)


def test_update_state_mean_raises():
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    m = DummyMean()
    with pytest.raises(TorchMetricsUserError, match="mean"):
        m.update_state(m.init_state(), np.asarray(1.0))


# ------------------------------------------------------- coalesced fast path
# (the full parity fuzz lives in tests/test_coalesced_sync.py; these pin the
# plane-2 entry point's behavior)


def test_process_sync_coalesces_multi_leaf_state():
    """A faithful replay world rides the coalesced plane: one metadata gather
    plus one collective per dtype bucket, per-leaf results preserved."""
    from torchmetrics_tpu.parallel import coalesce as C

    states = [
        {"a": jnp.asarray([1.0, 2.0]), "b": jnp.asarray(3.0), "c": jnp.asarray([1], jnp.int32)},
        {"a": jnp.asarray([10.0, 20.0]), "b": jnp.asarray(7.0), "c": jnp.asarray([4], jnp.int32)},
    ]
    reds = {"a": "sum", "b": "max", "c": "sum"}

    class World:
        def __init__(self):
            self.calls = 0

        def __call__(self, v, g=None):
            k = self.calls
            self.calls += 1
            if k == 0:
                self.metas = [C.build_local_metadata([s], [reds]) for s in states]
                return [jnp.asarray(m) for m in self.metas]
            return [C.build_bucket_payload([s], [reds], k - 1, self.metas) for s in states]

    w = World()
    out = _sync.process_sync(dict(states[0]), reds, dist_sync_fn=w)
    assert w.calls == 3  # metadata + f32 bucket + i32 bucket (5 leaves total)
    assert np.allclose(np.asarray(out["a"]), [11.0, 22.0])
    assert float(out["b"]) == 7.0 and int(out["c"][0]) == 5


def test_process_sync_per_leaf_fallback_keeps_injection_contract():
    """Value-mutating fakes (the reference seam's classic shape) keep working
    byte-for-byte through the per-leaf fallback."""
    m = DummyMean(dist_sync_fn=_fake_gather_factory(3))
    m.update(np.asarray(4.0))
    m.sync(distributed_available=lambda: True)
    assert np.isclose(float(m._state["v"]), 5.0)
    m.unsync()
