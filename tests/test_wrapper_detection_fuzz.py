"""Wrappers over detection metrics (VERDICT r4 #7c).

BootStrapper resamples detection inputs at the IMAGE level (the evaluation
sample unit) — the reference's tensor-only resampler would resample boxes
WITHIN images, which is not a bootstrap of the sample (see
wrappers/bootstrapping.py docstring). Verified by replaying the wrapper's
seeded sampler manually and comparing replica-for-replica. ClasswiseWrapper
labels mAP's `*_per_class` outputs per class (the reference's tensor-only
wrapper degenerates to enumerating dict keys there).
"""

from __future__ import annotations

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax

from torchmetrics_tpu.detection import MeanAveragePrecision
from torchmetrics_tpu.wrappers import BootStrapper, ClasswiseWrapper
from torchmetrics_tpu.wrappers.bootstrapping import _bootstrap_sampler

from conftest import seed_all

N_CLS = 3


def _det_dataset(rng, n_imgs, dense_classes=True):
    preds, target = [], []
    for _ in range(n_imgs):
        # every class appears in every image so bootstrap draws cannot drop a
        # class (per-class output shapes stay stackable across replicas)
        labels = np.arange(N_CLS, dtype=np.int32) if dense_classes else rng.integers(0, N_CLS, 3).astype(np.int32)
        ng = len(labels)
        gt = np.concatenate([rng.uniform(0, 200, (ng, 2)), np.zeros((ng, 2))], -1).astype(np.float32)
        gt[:, 2:] = gt[:, :2] + rng.uniform(10, 80, (ng, 2))
        nd = ng + int(rng.integers(0, 3))
        dt_labels = np.concatenate([labels, rng.integers(0, N_CLS, nd - ng).astype(np.int32)])
        dt = np.concatenate([gt, rng.uniform(0, 200, (nd - ng, 4)).astype(np.float32)]) if nd > ng else gt.copy()
        dt = dt + rng.uniform(-8, 8, dt.shape).astype(np.float32)
        preds.append({
            "boxes": jnp.asarray(dt),
            "scores": jnp.asarray(rng.uniform(0.1, 1, nd).astype(np.float32)),
            "labels": jnp.asarray(dt_labels),
        })
        target.append({"boxes": jnp.asarray(gt), "labels": jnp.asarray(labels)})
    return preds, target


@pytest.mark.parametrize("strategy", ["poisson", "multinomial"])
def test_bootstrapper_over_map_matches_manual_replicas(strategy):
    rng = seed_all(31)
    preds, target = _det_dataset(rng, 24)

    wrapper = BootStrapper(
        MeanAveragePrecision(), num_bootstraps=4, sampling_strategy=strategy, seed=99, raw=True
    )
    wrapper.update(preds, target)
    out = wrapper.compute()

    # replay: same seeded sampler stream, image-level resampling, plain metrics
    replay_rng = np.random.default_rng(99)
    manual_maps = []
    for _ in range(4):
        idx = _bootstrap_sampler(replay_rng, 24, strategy)
        if idx.size == 0:
            continue
        m = MeanAveragePrecision()
        m.update([preds[int(i)] for i in idx], [target[int(i)] for i in idx])
        manual_maps.append(float(m.compute()["map"]))

    raw_maps = np.asarray(out["raw"]["map"], np.float64)
    np.testing.assert_allclose(raw_maps, np.asarray(manual_maps), atol=1e-7)
    np.testing.assert_allclose(float(out["mean"]["map"]), np.mean(manual_maps), atol=1e-6)
    np.testing.assert_allclose(float(out["std"]["map"]), np.std(manual_maps, ddof=1), atol=1e-6)
    assert np.std(manual_maps) > 0 or len(set(manual_maps)) == 1  # resamples actually differ


def test_bootstrapper_over_map_merges_across_shards():
    rng = seed_all(37)
    preds, target = _det_dataset(rng, 16)

    def fresh():
        return BootStrapper(MeanAveragePrecision(), num_bootstraps=3, sampling_strategy="poisson", seed=5)

    a, b = fresh(), fresh()
    a.update(preds[:8], target[:8])
    b._rng = a._rng  # continue the same sampler stream, like one rank's sequential updates
    b.update(preds[8:], target[8:])
    oneshot = fresh()
    oneshot.update(preds[:8], target[:8])
    oneshot.update(preds[8:], target[8:])

    a.merge_state(b)
    got = jax.tree.map(np.asarray, a.compute())
    want = jax.tree.map(np.asarray, oneshot.compute())
    jax.tree.map(lambda x, y: np.testing.assert_allclose(x, y, atol=1e-7), got, want)


def test_classwise_wrapper_over_map_labels_per_class():
    rng = seed_all(41)
    preds, target = _det_dataset(rng, 12)

    plain = MeanAveragePrecision(class_metrics=True)
    plain.update(preds, target)
    ref = {k: np.asarray(v) for k, v in plain.compute().items()}

    wrapped = ClasswiseWrapper(MeanAveragePrecision(class_metrics=True), labels=["car", "dog", "cat"])
    wrapped.update(preds, target)
    out = {k: np.asarray(v) for k, v in wrapped.compute().items()}

    for i, lab in enumerate(["car", "dog", "cat"]):
        np.testing.assert_allclose(out[f"meanaverageprecision_map_{lab}"], ref["map_per_class"][i], atol=0)
        np.testing.assert_allclose(out[f"meanaverageprecision_mar_100_{lab}"], ref["mar_100_per_class"][i], atol=0)
    # scalars pass through unchanged; the classes vector is consumed for labeling
    # AND still emitted under its prefixed name (ADVICE round 5)
    np.testing.assert_allclose(out["meanaverageprecision_map"], ref["map"], atol=0)
    np.testing.assert_allclose(out["meanaverageprecision_classes"], ref["classes"], atol=0)
