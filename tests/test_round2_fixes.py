"""Round-2 ADVICE regression tests: ragged-query RetrievalPrecision denominator,
RetrievalRecallAtFixedPrecision tie-breaking, EER micro/macro averaging, and
min_recall validation messages."""

import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.classification import EER, MulticlassEER
from torchmetrics_tpu.classification.precision_fixed_recall import (
    BinaryPrecisionAtFixedRecall,
    MulticlassPrecisionAtFixedRecall,
)
from torchmetrics_tpu.functional.classification.eer import eer, multiclass_eer
from torchmetrics_tpu.retrieval import RetrievalPrecision, RetrievalRecallAtFixedPrecision


def test_retrieval_precision_ragged_queries_default_topk():
    """top_k=None must divide by each query's own document count (ADVICE high):
    query A: 3 docs 1 relevant → 1/3; query B: 6 docs 4 relevant → 4/6; mean = 1/2."""
    indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1, 1, 1])
    preds = jnp.asarray([0.9, 0.8, 0.7, 0.9, 0.8, 0.7, 0.6, 0.5, 0.4])
    target = jnp.asarray([1, 0, 0, 1, 1, 1, 1, 0, 0])
    m = RetrievalPrecision()
    m.update(preds, target, indexes=indexes)
    assert np.isclose(float(m.compute()), 0.5)


def test_retrieval_precision_explicit_topk_unchanged():
    indexes = jnp.asarray([0, 0, 0, 0])
    preds = jnp.asarray([0.9, 0.8, 0.7, 0.6])
    target = jnp.asarray([1, 1, 0, 0])
    m = RetrievalPrecision(top_k=2)
    m.update(preds, target, indexes=indexes)
    assert np.isclose(float(m.compute()), 1.0)


def test_recall_at_fixed_precision_prefers_largest_k_tie():
    """Reference max((r, k)) picks the LARGEST k among max-recall ties (ADVICE low)."""
    indexes = jnp.asarray([0, 0, 0, 0])
    preds = jnp.asarray([0.9, 0.8, 0.7, 0.6])
    target = jnp.asarray([1, 1, 0, 0])
    # recall@k = [0.5, 1, 1, 1]; precision@k = [1, 1, 2/3, 0.5]; min_precision=0.6
    # feasible ks = 1,2,3; max recall 1.0 at k=2 and k=3 → best_k must be 3
    m = RetrievalRecallAtFixedPrecision(min_precision=0.6, max_k=4)
    m.update(preds, target, indexes=indexes)
    r, k = m.compute()
    assert np.isclose(float(r), 1.0)
    assert int(k) == 3


def test_recall_at_fixed_precision_zero_recall_clamps_to_max_k():
    indexes = jnp.asarray([0, 0, 0])
    preds = jnp.asarray([0.9, 0.8, 0.7])
    target = jnp.asarray([0, 0, 1])
    # only relevant doc ranked last: recall@k = [0,0,1], precision@k = [0,0,1/3]
    # min_precision=0.9 infeasible everywhere → recall 0, best_k = max_k
    m = RetrievalRecallAtFixedPrecision(min_precision=0.9, max_k=3)
    m.update(preds, target, indexes=indexes)
    r, k = m.compute()
    assert float(r) == 0.0
    assert int(k) == 3


def _mc_scores(n=60, c=4, seed=7):
    rng = np.random.default_rng(seed)
    logits = rng.normal(size=(n, c)).astype(np.float32)
    preds = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    target = rng.integers(0, c, n)
    return jnp.asarray(preds), jnp.asarray(target)


def test_multiclass_eer_micro_scalar():
    preds, target = _mc_scores()
    out = multiclass_eer(preds, target, num_classes=4, thresholds=20, average="micro")
    assert out.ndim == 0
    # micro == binary EER over the one-hot flattened problem
    from torchmetrics_tpu.functional.classification.eer import binary_eer

    onehot = jnp.zeros((target.shape[0], 4)).at[jnp.arange(target.shape[0]), target].set(1)
    ref = binary_eer(preds.ravel(), onehot.ravel().astype(jnp.int32), thresholds=20)
    assert np.isclose(float(out), float(ref), atol=1e-6)


def test_multiclass_eer_macro_scalar_and_none_per_class():
    preds, target = _mc_scores()
    macro = multiclass_eer(preds, target, num_classes=4, thresholds=20, average="macro")
    per_class = multiclass_eer(preds, target, num_classes=4, thresholds=20, average=None)
    assert macro.ndim == 0
    assert per_class.shape == (4,)


@pytest.mark.parametrize("average", ["micro", "macro"])
def test_multiclass_eer_class_matches_functional(average):
    preds, target = _mc_scores()
    m = MulticlassEER(num_classes=4, average=average, thresholds=20)
    m.update(preds, target)
    ref = multiclass_eer(preds, target, num_classes=4, thresholds=20, average=average)
    assert np.isclose(float(m.compute()), float(ref), atol=1e-6)


def test_eer_facade_plumbs_average():
    preds, target = _mc_scores()
    m = EER(task="multiclass", num_classes=4, average="micro", thresholds=20)
    m.update(preds, target)
    f = eer(preds, target, task="multiclass", num_classes=4, average="micro", thresholds=20)
    assert np.isclose(float(m.compute()), float(f), atol=1e-6)


def test_multiclass_eer_invalid_average_raises():
    with pytest.raises(ValueError, match="average"):
        MulticlassEER(num_classes=4, average="weighted")


@pytest.mark.parametrize(
    "ctor",
    [
        lambda: BinaryPrecisionAtFixedRecall(min_recall=1.5),
        lambda: MulticlassPrecisionAtFixedRecall(num_classes=3, min_recall=-0.1),
    ],
)
def test_precision_at_fixed_recall_error_names_min_recall(ctor):
    with pytest.raises(ValueError, match="min_recall"):
        ctor()
