"""Compute-group formation fuzz (VERDICT r4 #7a).

Random subsets of 8-15 multiclass metrics are built as a MetricCollection here
AND in the reference (tests/oracle.py), fed identical data, and compared on:

- the GROUP PARTITION the state-equality merge discovers (reference
  collections.py:269-356) — same groups, member-for-member;
- update-count economy — after the groups are checked, only one state dict per
  group exists (members alias their leader's states);
- every computed value, name-for-name, against the reference.

The pool mixes state families deliberately: stat-scores sharers, confusion-matrix
sharers, binned-curve sharers at TWO different threshold counts (same-family
metrics with different binning must NOT merge), and loners.
"""

from __future__ import annotations

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

import torchmetrics_tpu as tm
from torchmetrics_tpu import MetricCollection

from conftest import seed_all
from oracle import require_oracle

C = 5
N = 64

# name -> (our ctor, reference ctor factory taking the reference module)
POOL = {
    "acc_macro": (lambda: tm.MulticlassAccuracy(C), lambda R: R.MulticlassAccuracy(C)),
    "acc_micro": (lambda: tm.MulticlassAccuracy(C, average="micro"), lambda R: R.MulticlassAccuracy(C, average="micro")),
    "precision": (lambda: tm.MulticlassPrecision(C), lambda R: R.MulticlassPrecision(C)),
    "recall": (lambda: tm.MulticlassRecall(C), lambda R: R.MulticlassRecall(C)),
    "f1": (lambda: tm.MulticlassF1Score(C), lambda R: R.MulticlassF1Score(C)),
    "specificity": (lambda: tm.MulticlassSpecificity(C), lambda R: R.MulticlassSpecificity(C)),
    "stat_scores": (lambda: tm.MulticlassStatScores(C), lambda R: R.MulticlassStatScores(C)),
    "confmat": (lambda: tm.MulticlassConfusionMatrix(C), lambda R: R.MulticlassConfusionMatrix(C)),
    "cohen_kappa": (lambda: tm.MulticlassCohenKappa(C), lambda R: R.MulticlassCohenKappa(C)),
    "matthews": (lambda: tm.MulticlassMatthewsCorrCoef(C), lambda R: R.MulticlassMatthewsCorrCoef(C)),
    "jaccard": (lambda: tm.MulticlassJaccardIndex(C), lambda R: R.MulticlassJaccardIndex(C)),
    "auroc_t17": (lambda: tm.MulticlassAUROC(C, thresholds=17), lambda R: R.MulticlassAUROC(C, thresholds=17)),
    "ap_t17": (lambda: tm.MulticlassAveragePrecision(C, thresholds=17), lambda R: R.MulticlassAveragePrecision(C, thresholds=17)),
    "roc_t17": (lambda: tm.MulticlassROC(C, thresholds=17), lambda R: R.MulticlassROC(C, thresholds=17)),
    "auroc_t31": (lambda: tm.MulticlassAUROC(C, thresholds=31), lambda R: R.MulticlassAUROC(C, thresholds=31)),
    "ap_t31": (lambda: tm.MulticlassAveragePrecision(C, thresholds=31), lambda R: R.MulticlassAveragePrecision(C, thresholds=31)),
    "calibration": (lambda: tm.MulticlassCalibrationError(C, n_bins=10), lambda R: R.MulticlassCalibrationError(C, n_bins=10)),
    "hinge": (lambda: tm.MulticlassHingeLoss(C), lambda R: R.MulticlassHingeLoss(C)),
    "exact_match": (lambda: tm.MulticlassExactMatch(C), lambda R: R.MulticlassExactMatch(C)),
}


def _partition(groups, modules):
    """compute_groups dict -> canonical frozenset-of-frozensets of member names."""
    covered = frozenset(frozenset(members) for members in groups.values())
    assert sum(len(g) for g in covered) == len(modules)
    return covered


def _flatten(prefix, value, out):
    import torch

    if isinstance(value, dict):
        for k, v in value.items():
            _flatten(f"{prefix}.{k}", v, out)
    elif isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            _flatten(f"{prefix}.{i}", v, out)
    else:
        out[prefix] = value.numpy() if isinstance(value, torch.Tensor) else np.asarray(value)


@pytest.mark.parametrize("trial", range(6))
def test_compute_group_formation_matches_reference(trial):
    ref_tm = require_oracle()
    import torch

    from torchmetrics.classification import __dict__ as _refns  # noqa: F401

    R = __import__("torchmetrics").classification
    rng = seed_all(4200 + trial)
    names = sorted(rng.choice(sorted(POOL), size=int(rng.integers(8, 16)), replace=False).tolist())

    ours = MetricCollection({n: POOL[n][0]() for n in names})
    theirs = ref_tm.MetricCollection({n: POOL[n][1](R) for n in names})

    for _ in range(3):
        logits = rng.normal(size=(N, C)).astype(np.float32)
        probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        target = rng.integers(0, C, N).astype(np.int64)
        ours.update(jnp.asarray(probs), jnp.asarray(target.astype(np.int32)))
        theirs.update(torch.from_numpy(probs), torch.from_numpy(target))

    # 1) group partition: ours must be a COARSENING of the reference's — every
    # group the reference merges, we merge too (never split a shareable state),
    # and we may merge strictly more. Known refinement: the reference's
    # average="micro" stat-scores metrics keep scalar states (can't share with
    # macro's per-class vectors); ours keep per-class states for micro too and
    # reduce at compute, so micro joins the stat-scores group — one fewer state
    # to update, values identical (asserted below).
    ours_part = _partition(ours.compute_groups, names)
    ref_part = _partition(theirs.compute_groups, names)
    for ref_group in ref_part:
        assert any(ref_group <= our_group for our_group in ours_part), (
            f"reference merges {sorted(ref_group)} but ours splits it:\n"
            f"ours {sorted(map(sorted, ours_part))}\nref  {sorted(map(sorted, ref_part))}"
        )
    assert len(ours_part) <= len(ref_part)

    # 2) update economy: members alias their leader's state dict — one state per
    # group, not one per metric (reference collections.py:338-356)
    distinct_states = {id(ours[name]._state) for name in names}
    assert len(distinct_states) == len(ours.compute_groups), (
        f"{len(distinct_states)} distinct state dicts for {len(ours.compute_groups)} groups"
    )

    # 3) every value matches the reference
    got, want = {}, {}
    for key, val in ours.compute().items():
        _flatten(key, val, got)
    for key, val in theirs.compute().items():
        _flatten(key, val, want)
    assert got.keys() == want.keys()
    for key in want:
        np.testing.assert_allclose(got[key], want[key], atol=1e-6, err_msg=f"trial {trial}: {key}")

    # 4) compute() must not have corrupted shared state: a fourth update and
    # recompute still agrees (state-copy semantics, reference collections.py:250)
    logits = rng.normal(size=(N, C)).astype(np.float32)
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    target = rng.integers(0, C, N).astype(np.int64)
    ours.update(jnp.asarray(probs), jnp.asarray(target.astype(np.int32)))
    theirs.update(torch.from_numpy(probs), torch.from_numpy(target))
    got2, want2 = {}, {}
    for key, val in ours.compute().items():
        _flatten(key, val, got2)
    for key, val in theirs.compute().items():
        _flatten(key, val, want2)
    for key in want2:
        np.testing.assert_allclose(got2[key], want2[key], atol=1e-6, err_msg=f"trial {trial} post-compute: {key}")
