"""Reliability layer: exception classification, retry/backoff, recovery parity.

Acceptance (ISSUE 1): with a transient error injected on the 3rd update dispatch and
on one sync participant, the retried run completes and its compute() is BITWISE
identical to the uninterrupted run, for one metric per domain (classification,
regression, aggregation) and one fused MetricCollection; deterministic errors are
never retried (classifier pinned in both directions); the bench driver's retry
wrapper recovers an injected subprocess crash and records attempts/recovered_from.
"""

from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np
import pytest

import torchmetrics_tpu as tm
from torchmetrics_tpu import MetricCollection
from torchmetrics_tpu.reliability import (
    DETERMINISTIC,
    TRANSIENT,
    FlakyGather,
    ReliabilityConfig,
    RetryPolicy,
    classify_exception,
    inject_dispatch_fault,
    is_transient_error_text,
    make_transient_error,
)
from torchmetrics_tpu.utilities.exceptions import (
    StateCorruptionError,
    TorchMetricsUserError,
    TransientRuntimeError,
)

pytestmark = pytest.mark.faults

NUM_CLASSES = 5


def _policy(**kw):
    kw.setdefault("max_attempts", 3)
    kw.setdefault("sleep_fn", lambda s: None)  # tests never actually wait
    return RetryPolicy(**kw)


def _rel(**kw):
    return ReliabilityConfig(retry=_policy(), **kw)


# --------------------------------------------------------------- classification


class TestClassifier:
    """Both directions pinned: transient retries, deterministic never."""

    @pytest.mark.parametrize(
        "exc",
        [
            make_transient_error(),  # the round-5 crash message, verbatim shape
            TransientRuntimeError("anything at all"),  # transient by type
            RuntimeError("INTERNAL: stream terminated by RST_STREAM"),
            RuntimeError("UNAVAILABLE: connection reset by peer"),
            RuntimeError("DEADLINE_EXCEEDED: compile request timed out"),
            RuntimeError("ABORTED: coordination service heartbeat timeout"),
            ConnectionResetError("peer went away"),
            BrokenPipeError("broken pipe"),
            TimeoutError("rpc timed out"),
            OSError("Connection reset during recvmsg"),
        ],
    )
    def test_transient(self, exc):
        assert classify_exception(exc) == TRANSIENT

    @pytest.mark.parametrize(
        "exc",
        [
            ValueError("Expected argument `num_classes` to be an integer"),
            TypeError("unsupported operand"),
            KeyError("tp"),
            IndexError("out of range"),
            AssertionError("shapes differ"),
            TorchMetricsUserError("Metric shouldn't be synced"),
            StateCorruptionError("state 'tp' contains non-finite values"),
            # deterministic runtime statuses stay deterministic even though they
            # arrive in the same JaxRuntimeError/RuntimeError wrapper
            RuntimeError("INVALID_ARGUMENT: shape mismatch in parameter 0"),
            RuntimeError("some unknown error with no status prefix"),
            # a deterministic status wins even when a transient-looking fragment
            # appears later in the message
            RuntimeError("INVALID_ARGUMENT: while handling connection reset"),
        ],
    )
    def test_deterministic(self, exc):
        assert classify_exception(exc) == DETERMINISTIC

    def test_error_text_classifier(self):
        assert is_transient_error_text(
            "JaxRuntimeError: INTERNAL: ... response body closed before all bytes were read"
        )
        assert not is_transient_error_text("ValueError: Expected `preds` to be a float tensor")


class TestBackoffSchedule:
    def test_exponential_capped_and_deterministic(self):
        pol = RetryPolicy(max_attempts=6, backoff_base=0.1, backoff_factor=2.0, max_backoff=0.5, jitter=0.0)
        assert pol.schedule() == pytest.approx([0.1, 0.2, 0.4, 0.5, 0.5])
        # deterministic: the same policy produces the same schedule, always
        assert pol.schedule() == pol.schedule()

    def test_jitter_bounded_and_deterministic(self):
        pol = RetryPolicy(max_attempts=5, backoff_base=0.1, backoff_factor=2.0, max_backoff=10.0, jitter=0.2)
        raw = [0.1, 0.2, 0.4, 0.8]
        for attempt, base in zip(range(1, 5), raw):
            d = pol.delay_for(attempt)
            assert base * 0.8 <= d <= base * 1.2
            assert d == pol.delay_for(attempt)  # no RNG, no wall clock

    def test_sleeps_actually_happen_on_retry(self):
        slept = []
        pol = RetryPolicy(max_attempts=3, backoff_base=0.01, jitter=0.0, sleep_fn=slept.append)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise make_transient_error()
            return "ok"

        assert pol.call(flaky) == "ok"
        assert slept == pytest.approx([0.01, 0.02])

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=2.0)


# ------------------------------------------------------- recovery parity (update)


def _cls_data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    preds = jnp.asarray(rng.normal(size=(n, NUM_CLASSES)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, n, dtype=np.int32))
    return preds, target


PARITY_CASES = {
    # one metric per domain (classification / regression / aggregation)
    "classification": (lambda **kw: tm.MulticlassAccuracy(NUM_CLASSES, average="micro", **kw), _cls_data),
    "regression": (
        lambda **kw: tm.MeanSquaredError(**kw),
        lambda: (
            jnp.asarray(np.random.default_rng(1).normal(size=64).astype(np.float32)),
            jnp.asarray(np.random.default_rng(2).normal(size=64).astype(np.float32)),
        ),
    ),
    "aggregation": (
        lambda **kw: tm.MeanMetric(**kw),
        lambda: (jnp.asarray(np.random.default_rng(3).normal(size=32).astype(np.float32)),),
    ),
}


@pytest.mark.parametrize("domain", sorted(PARITY_CASES))
def test_retry_recovers_bitwise_identical_update(domain):
    """Transient fault on the 3rd update dispatch: the retried run's compute() is
    bitwise identical to the uninterrupted run's."""
    make, data = PARITY_CASES[domain]
    args = data()

    plain = make()
    for _ in range(5):
        plain.update(*args)
    want = np.asarray(plain.compute())

    faulted = make(reliability=_rel())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        with inject_dispatch_fault(faulted, fail_on=3, tag="update") as hook:
            for _ in range(5):
                faulted.update(*args)
    assert hook.raised == 1
    got = np.asarray(faulted.compute())
    np.testing.assert_array_equal(got, want)
    assert got.dtype == want.dtype
    assert faulted.update_count == plain.update_count


def test_retry_recovers_forward_and_compute_boundaries():
    preds, target = _cls_data()
    plain = tm.MulticlassAccuracy(NUM_CLASSES, average="micro")
    vals_plain = [np.asarray(plain.forward(preds, target)) for _ in range(3)]

    faulted = tm.MulticlassAccuracy(NUM_CLASSES, average="micro", reliability=_rel())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        with inject_dispatch_fault(faulted, fail_on=2, tag="forward") as hook:
            vals = [np.asarray(faulted.forward(preds, target)) for _ in range(3)]
        assert hook.raised == 1
        for got, want in zip(vals, vals_plain):
            np.testing.assert_array_equal(got, want)
        # and a fault at the compute boundary
        with inject_dispatch_fault(faulted, fail_on=1, tag="compute") as hook:
            got = np.asarray(faulted.compute())
        assert hook.raised == 1
    np.testing.assert_array_equal(got, np.asarray(plain.compute()))


def test_retry_recovers_fused_collection():
    """One fused MetricCollection: fault the compute-group leader's dispatch; the
    recovered collection matches the uninterrupted one key for key, bit for bit."""
    preds, target = _cls_data()

    def members(**kw):
        return {
            "acc": tm.MulticlassAccuracy(NUM_CLASSES, average="micro", **kw),
            "f1": tm.MulticlassF1Score(NUM_CLASSES, average="macro", **kw),
            "auroc": tm.MulticlassAUROC(NUM_CLASSES, thresholds=16, **kw),
            "confmat": tm.MulticlassConfusionMatrix(NUM_CLASSES, **kw),
        }

    plain = MetricCollection(members())
    for _ in range(4):
        plain.update(preds, target)
    want = {k: np.asarray(v) for k, v in plain.compute().items()}

    coll = MetricCollection(members(reliability=_rel()))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        coll.update(preds, target)  # derive compute groups first
        leader = coll[list(coll.compute_groups.values())[0][0]]
        with inject_dispatch_fault(leader, fail_on=2, tag="update") as hook:
            for _ in range(3):
                coll.update(preds, target)
    assert hook.raised == 1
    got = {k: np.asarray(v) for k, v in coll.compute().items()}
    assert set(got) == set(want)
    for key in want:
        np.testing.assert_array_equal(got[key], want[key], err_msg=key)


# ------------------------------------------------------- recovery parity (sync)


def _fake_world_gather(world):
    def gather(value, process_group=None):
        return [jnp.asarray(value) + i for i in range(world)]

    return gather


def test_retry_recovers_dropped_sync_participant():
    """Transient participant drop during the process gather: sync retries and the
    synced value is bitwise identical to a never-faulted sync."""
    preds, target = _cls_data()

    def build(gather):
        return tm.MulticlassAccuracy(
            NUM_CLASSES,
            average="micro",
            dist_sync_fn=gather,
            distributed_available_fn=lambda: True,
            reliability=_rel(),
        )

    clean = build(_fake_world_gather(2))
    clean.update(preds, target)
    want = np.asarray(clean.compute())

    flaky = FlakyGather(inner=_fake_world_gather(2), fail_times=1)
    faulted = build(flaky)
    faulted.update(preds, target)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        got = np.asarray(faulted.compute())
    assert flaky.failures == 1
    np.testing.assert_array_equal(got, want)


def test_dropped_participant_without_retry_raises():
    """No ReliabilityConfig → the drop propagates (today's behavior, preserved)."""
    preds, target = _cls_data()
    m = tm.MulticlassAccuracy(
        NUM_CLASSES,
        average="micro",
        dist_sync_fn=FlakyGather(inner=_fake_world_gather(2), fail_times=1),
        distributed_available_fn=lambda: True,
    )
    m.update(preds, target)
    with pytest.raises(TransientRuntimeError, match="participant dropped"):
        m.compute()


# ----------------------------------------------------- deterministic: no retry


class _BadInput(tm.Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("t", default=np.zeros(()), dist_reduce_fx="sum")
        self.attempts = 0

    def _batch_state(self, x):
        return {"t": jnp.asarray(x).sum()}

    def _prepare_inputs(self, *args, **kwargs):
        self.attempts += 1
        raise ValueError("deterministic user error: bad shape")

    def _compute(self, state):
        return state["t"]


def test_deterministic_errors_are_not_retried():
    m = _BadInput(reliability=_rel())
    with pytest.raises(ValueError, match="deterministic user error"):
        m.update(jnp.ones(3))
    assert m.attempts == 1  # exactly one attempt — no retry loop

    # same through the dispatch seam: a deterministic exc_factory raises once
    m2 = tm.MulticlassAccuracy(NUM_CLASSES, average="micro", reliability=_rel())
    preds, target = _cls_data()
    with inject_dispatch_fault(m2, fail_on=1, exc_factory=lambda: TypeError("nope")) as hook:
        with pytest.raises(TypeError):
            m2.update(preds, target)
    assert hook.calls == 1


def test_transient_without_policy_propagates():
    """Reliability off (default): the transient error kills the update, as today."""
    preds, target = _cls_data()
    m = tm.MulticlassAccuracy(NUM_CLASSES, average="micro")
    with inject_dispatch_fault(m, fail_on=1) as hook:
        with pytest.raises(TransientRuntimeError):
            m.update(preds, target)
    assert hook.calls == 1


def test_retry_budget_exhaustion_reraises():
    preds, target = _cls_data()
    m = tm.MulticlassAccuracy(NUM_CLASSES, average="micro", reliability=_rel())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        with inject_dispatch_fault(m, fail_on=1, times=99) as hook:
            with pytest.raises(TransientRuntimeError):
                m.update(preds, target)
    assert hook.calls == 3  # max_attempts, then the original error surfaces


# ------------------------------------------------------------------ bench driver


def test_bench_retry_wrapper_records_recovery():
    """The bench driver's subprocess retry: an injected transient crash on the first
    attempt is recovered and flagged recovered_from, with attempts recorded —
    the direct fix for the round-5 FID headline loss."""
    import bench

    out = bench._run_in_subprocess("_fault_selftest")
    assert out.get("ok") is True
    assert out["attempts"] == 2
    assert len(out["recovered_from"]) == 1
    assert "response body closed" in out["recovered_from"][0]


def test_bench_fid_gets_one_extra_transient_attempt(monkeypatch):
    """PR 6 satellite: the fid probe's remote_compile transport flake gets ONE
    re-attempt beyond the global budget before the {"error", "transient"}
    headline is emitted — and deterministic failures never consume it."""
    import bench

    calls = []

    def fake_attempt(name, attempt):
        calls.append(attempt)
        return {"error": "INTERNAL: stream terminated by RST_STREAM", "transient": True}

    monkeypatch.setattr(bench, "_attempt_subprocess", fake_attempt)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    out = bench._run_in_subprocess("fid_inception_fwd")
    assert out["attempts"] == bench.MAX_ATTEMPTS + 1 == len(calls)
    assert out["transient"] is True and "error" in out

    # a non-fid config keeps the global budget
    calls.clear()
    out = bench._run_in_subprocess("coco_map_synthetic")
    assert out["attempts"] == bench.MAX_ATTEMPTS == len(calls)

    # the extra shot can actually SAVE the headline on the final attempt
    def flaky_until_last(name, attempt):
        calls.append(attempt)
        if attempt <= bench.MAX_ATTEMPTS:
            return {"error": "INTERNAL: stream terminated by RST_STREAM", "transient": True}
        return {"ok": True}

    calls.clear()
    monkeypatch.setattr(bench, "_attempt_subprocess", flaky_until_last)
    out = bench._run_in_subprocess("fid_inception_fwd")
    assert out.get("ok") is True and out["attempts"] == bench.MAX_ATTEMPTS + 1
    assert len(out["recovered_from"]) == bench.MAX_ATTEMPTS

    # deterministic failures surface immediately — no extra attempt burned
    calls.clear()
    monkeypatch.setattr(
        bench, "_attempt_subprocess",
        lambda name, attempt: (calls.append(attempt), {"error": "INVALID_ARGUMENT: bad shapes", "transient": False})[1],
    )
    out = bench._run_in_subprocess("fid_inception_fwd")
    assert out["attempts"] == 1 == len(calls)


def test_bench_config_names_hidden_from_main_run():
    import bench

    public = [n for n in bench.CONFIGS if not n.startswith("_")]
    assert "_fault_selftest" in bench.CONFIGS
    assert "_fault_selftest" not in public
    assert "fid_inception_fwd" in public  # the config whose loss motivated all this


def test_bench_classifier_mirrors_canonical_markers():
    """bench.py's stdlib-only classifier must stay in lockstep with the canonical
    one in reliability.retry (the driver parent deliberately avoids importing the
    package, so the marker lists are mirrored — this pins them together)."""
    import bench
    from torchmetrics_tpu.reliability import retry as retry_mod

    assert tuple(bench._TRANSIENT_MARKERS) == retry_mod._TRANSIENT_MESSAGE_MARKERS
    assert tuple(bench._DETERMINISTIC_MARKERS) == retry_mod._DETERMINISTIC_MESSAGE_MARKERS
    for msg in (
        "INTERNAL: response body closed before all bytes were read",
        "UNAVAILABLE: connection reset by peer",
        "INVALID_ARGUMENT: shapes do not match",
        "a plain user error",
    ):
        assert bench._is_transient_error_text(msg) == is_transient_error_text(msg)


def test_exhausted_retry_leaves_usable_state():
    """When the budget runs out mid-eval, the metric re-raises at its LAST GOOD
    state (the failed batch is rolled back) and stays usable — the donated live
    buffers are replaced by the undonated backup before the re-raise."""
    preds, target = _cls_data()
    third = len(target) // 3
    ref = tm.MulticlassAccuracy(NUM_CLASSES, average="micro")
    ref.update(preds[:third], target[:third])
    ref.update(preds[2 * third :], target[2 * third :])  # middle batch never lands

    m = tm.MulticlassAccuracy(NUM_CLASSES, average="micro", reliability=_rel())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        m.update(preds[:third], target[:third])
        with inject_dispatch_fault(m, fail_on=1, times=99):
            with pytest.raises(TransientRuntimeError):
                m.update(preds[third : 2 * third], target[third : 2 * third])
        m.update(preds[2 * third :], target[2 * third :])  # still works after
    assert m._update_count == 2
    np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(ref.compute()))


def test_oom_is_deterministic_not_retried():
    """TPU/XLA RESOURCE_EXHAUSTED is the out-of-memory status — deterministic for
    a fixed workload; retrying an OOM just re-OOMs slower."""
    import bench

    msg = "RESOURCE_EXHAUSTED: Out of memory while trying to allocate 8589934592 bytes."
    assert classify_exception(RuntimeError(msg)) == "deterministic"
    assert not is_transient_error_text(msg)
    assert not bench._is_transient_error_text(msg)
