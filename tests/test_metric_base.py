"""Core Metric runtime tests (reference tests/unittests/bases/test_metric.py,
test_composition.py, test_hashing.py, test_saving_loading.py)."""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu import Metric
from torchmetrics_tpu.metric import CompositionalMetric
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError


class DummySum(Metric):
    """Parity with reference DummyMetricSum (testers.py:675-744)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("x", default=jnp.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, x):
        return {"x": jnp.asarray(x, jnp.float32).sum()}

    def _compute(self, state):
        return state["x"]


class DummyList(Metric):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("x", default=[], dist_reduce_fx="cat")

    def _batch_state(self, x):
        return {"x": jnp.atleast_1d(jnp.asarray(x, jnp.float32))}

    def _compute(self, state):
        return state["x"]


class DummyMax(Metric):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("m", default=-jnp.inf * jnp.ones(()), dist_reduce_fx="max")

    def _batch_state(self, x):
        return {"m": jnp.asarray(x, jnp.float32).max()}

    def _compute(self, state):
        return state["m"]


def test_add_state_validation():
    m = DummySum()
    with pytest.raises(ValueError, match="dist_reduce_fx"):
        m.add_state("bad", jnp.zeros(()), dist_reduce_fx="nope")
    with pytest.raises(ValueError, match="empty list"):
        m.add_state("bad", [1, 2])


def test_update_accumulates():
    m = DummySum()
    m.update(jnp.asarray([1.0, 2.0]))
    m.update(jnp.asarray([3.0]))
    assert float(m.compute()) == 6.0
    assert m.update_count == 2


def test_forward_returns_batch_value_and_accumulates():
    m = DummySum()
    v1 = m(jnp.asarray([1.0, 2.0]))
    assert float(v1) == 3.0
    v2 = m(jnp.asarray([4.0]))
    assert float(v2) == 4.0
    assert float(m.compute()) == 7.0


def test_reset():
    m = DummySum()
    m.update(jnp.asarray([5.0]))
    m.reset()
    assert m.update_count == 0
    assert float(m.compute()) == 0.0


def test_compute_cache_invalidated_on_update():
    m = DummySum()
    m.update(jnp.asarray([1.0]))
    assert float(m.compute()) == 1.0
    m.update(jnp.asarray([1.0]))
    assert float(m.compute()) == 2.0


def test_list_state_cat():
    m = DummyList()
    m.update(jnp.asarray([1.0, 2.0]))
    m.update(jnp.asarray([3.0]))
    np.testing.assert_array_equal(np.asarray(m.compute()), [1.0, 2.0, 3.0])


def test_max_state():
    m = DummyMax()
    m.update(jnp.asarray([1.0, 5.0]))
    m.update(jnp.asarray([3.0]))
    assert float(m.compute()) == 5.0


def test_merge_state_metric():
    a, b = DummySum(), DummySum()
    a.update(jnp.asarray([1.0]))
    b.update(jnp.asarray([2.0]))
    a.merge_state(b)
    assert float(a.compute()) == 3.0


def test_merge_state_dict():
    a = DummySum()
    a.update(jnp.asarray([1.0]))
    a.merge_state({"x": jnp.asarray(10.0)})
    assert float(a.compute()) == 11.0


def test_merge_state_wrong_type():
    a = DummySum()
    with pytest.raises(ValueError):
        a.merge_state(DummyMax())
    with pytest.raises(ValueError):
        a.merge_state(5)


def test_merge_state_list():
    a, b = DummyList(), DummyList()
    a.update(jnp.asarray([1.0]))
    b.update(jnp.asarray([2.0]))
    a.merge_state(b)
    np.testing.assert_array_equal(np.asarray(a.compute()), [1.0, 2.0])


def test_clone_independent():
    a = DummySum()
    a.update(jnp.asarray([1.0]))
    b = a.clone()
    b.update(jnp.asarray([2.0]))
    assert float(a.compute()) == 1.0
    assert float(b.compute()) == 3.0


def test_pickle_roundtrip():
    a = DummySum()
    a.update(jnp.asarray([4.0]))
    b = pickle.loads(pickle.dumps(a))
    assert float(b.compute()) == 4.0
    b.update(jnp.asarray([1.0]))
    assert float(b.compute()) == 5.0


def test_state_dict_persistence():
    a = DummySum()
    assert a.state_dict() == {}  # non-persistent by default (reference metric.py:919-990)
    a.persistent(True)
    a.update(jnp.asarray([2.0]))
    sd = a.state_dict()
    assert float(sd["x"]) == 2.0
    b = DummySum()
    b.persistent(True)
    b.load_state_dict(sd)
    assert float(b.compute()) == 2.0


def test_metric_state_property():
    a = DummySum()
    a.update(jnp.asarray([2.0]))
    assert float(a.metric_state["x"]) == 2.0


def test_composition_operators():
    a, b = DummySum(), DummySum()
    add = a + b
    a.update(jnp.asarray([1.0]))
    b.update(jnp.asarray([2.0]))
    assert float(add.compute()) == 3.0
    sub = a - b
    assert float(sub.compute()) == -1.0
    mul = a * 4
    assert float(mul.compute()) == 4.0
    radd = 10 + a
    assert float(radd.compute()) == 11.0
    neg = -a
    assert float(neg.compute()) == -1.0
    idx = DummyList()
    idx.update(jnp.asarray([5.0, 7.0]))
    assert float(idx[1].compute()) == 7.0


def test_composition_forward():
    a, b = DummySum(), DummySum()
    comp = a + b
    val = comp(jnp.asarray([2.0]))
    assert float(val) == 4.0
    assert isinstance(comp, CompositionalMetric)


def test_sync_noop_single_process():
    a = DummySum()
    a.update(jnp.asarray([1.0]))
    a.sync()  # no-op: not distributed
    assert not a._is_synced
    with pytest.raises(TorchMetricsUserError):
        a.unsync()


def test_double_sync_raises():
    a = DummySum()
    a.sync(should_sync=True, distributed_available=lambda: True, dist_sync_fn=lambda v, g: [v])
    with pytest.raises(TorchMetricsUserError):
        a.sync(distributed_available=lambda: True, dist_sync_fn=lambda v, g: [v])
    a.unsync()


def test_custom_dist_sync_fn():
    """dist_sync_fn seam (reference metric.py:133): simulate 2 ranks."""
    a = DummySum(dist_sync_fn=lambda v, g: [v, v], distributed_available_fn=lambda: True)
    a.update(jnp.asarray([3.0]))
    assert float(a.compute()) == 6.0  # doubled by fake 2-rank gather
    # after compute, unsync restored local state
    assert float(a._state["x"]) == 3.0


def test_update_while_synced_raises():
    a = DummySum(distributed_available_fn=lambda: True, dist_sync_fn=lambda v, g: [v])
    a.update(jnp.asarray([1.0]))
    a.sync()
    with pytest.raises(TorchMetricsUserError):
        a.update(jnp.asarray([1.0]))
    a.unsync()


def test_hash_changes_with_state():
    a = DummySum()
    h1 = hash(a)
    a.update(jnp.asarray([1.0]))
    h2 = hash(a)
    assert h1 != h2


def test_compute_without_update_warns():
    a = DummySum()
    with pytest.warns(UserWarning, match="before the ``update`` method"):
        a.compute()


def test_unexpected_kwargs_raise():
    with pytest.raises(ValueError, match="Unexpected keyword arguments"):
        DummySum(bogus=1)


def test_pure_ingraph_api():
    m = DummySum()
    state = m.init_state()
    state = jax.jit(m.update_state)(state, jnp.asarray([1.0, 2.0]))
    state = jax.jit(m.update_state)(state, jnp.asarray([3.0]))
    assert float(m.compute_state(state)) == 6.0


def test_pure_api_rejects_list_states():
    m = DummyList()
    with pytest.raises(TorchMetricsUserError):
        m.update_state(m.init_state(), jnp.asarray([1.0]))


def test_set_dtype():
    m = DummySum()
    m.set_dtype(jnp.bfloat16)
    m.update(jnp.asarray([1.0]))
    assert m.compute().dtype == jnp.bfloat16


def test_compute_on_cpu_offloads_list_states():
    """compute_on_cpu (reference metric.py:119) moves concat states to host after
    each update; the default keeps them on device."""
    import torchmetrics_tpu as tm

    m = tm.CatMetric(compute_on_cpu=True)
    m.update(jnp.asarray([1.0, 2.0]))
    m.update(jnp.asarray([3.0]))
    assert all(isinstance(e, np.ndarray) for e in m._state["value"])
    np.testing.assert_allclose(np.asarray(m.compute()), [1.0, 2.0, 3.0])

    on_device = tm.CatMetric()
    on_device.update(jnp.asarray([1.0, 2.0]))
    assert not isinstance(on_device._state["value"][0], np.ndarray)
