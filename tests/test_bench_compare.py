"""tools/bench_compare.py + multi-file tools/trace_report.py — stdlib-only
(deliberately no jax import: these are the CI smoke tests for the offline
tooling, runnable on a bare runner the way an operator would use them).

Fixture trajectories mirror the real ``BENCH_r0*.json`` driver shape
(``n``/``cmd``/``rc``/``tail``/``parsed``); the acceptance contract is that
``--check`` exits nonzero on an injected regression and zero on the repo's
real r01→r05 history."""

import glob
import importlib.util
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_COMPARE = os.path.join(REPO, "tools", "bench_compare.py")
TRACE_REPORT = os.path.join(REPO, "tools", "trace_report.py")


def _load(path):
    spec = importlib.util.spec_from_file_location(os.path.basename(path)[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_compare = _load(BENCH_COMPARE)


def _round(n, value, fused=27000.0, psum_ms=2.6, fid_bf16=6000.0, extra_overrides=None):
    """One driver-shaped round file body mirroring the real BENCH_r0*.json."""
    parsed = {
        "metric": "multiclass_accuracy_updates_per_sec",
        "value": value,
        "unit": "updates/s (batch=65536, C=5)",
        "vs_baseline": round(value / 423.0, 3),
        "extra": {
            "fused_collection_cifar10": {
                "updates_per_sec": fused,
                "unfused_4_dispatch_updates_per_sec": fused / 3.1,
                "fused_speedup_vs_unfused": 3.1,
            },
            "coco_map_synthetic": {"images_per_sec_update": 106000.0, "compute_sec_500imgs_80cls": 2.3},
            "fid_inception_fwd": {"images_per_sec_bfloat16": fid_bf16},
            "sync_allreduce_8dev_cpu": {"psum_latency_ms": psum_ms},
            "torch_cpu_proxy_updates_per_sec": 423.0,
        },
    }
    if extra_overrides:
        parsed["extra"].update(extra_overrides)
    return {"n": n, "cmd": "python bench.py", "rc": 0, "tail": json.dumps(parsed), "parsed": parsed}


def _write_rounds(tmp_path, rounds):
    paths = []
    for i, doc in enumerate(rounds, 1):
        p = tmp_path / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps(doc))
        paths.append(str(p))
    return paths


# ------------------------------------------------------------- unit behavior


def test_direction_inference():
    assert bench_compare.direction("value") == "higher"
    assert bench_compare.direction("extra.fused_collection_cifar10.updates_per_sec") == "higher"
    assert bench_compare.direction("extra.fused_collection_cifar10.fused_speedup_vs_unfused") == "higher"
    assert bench_compare.direction("extra.sync_allreduce_8dev_cpu.psum_latency_ms") == "lower"
    assert bench_compare.direction("extra.bertscore_clipscore.bertscore_compile_sec") == "lower"
    assert bench_compare.direction("extra.ours.telemetry.state_memory_bytes") is None  # informational
    assert bench_compare.direction("extra.fid_inception_fwd.attempts") is None
    # coalesced-sync config: the collective count gates (lower is better); the
    # deterministic leaf-count constants stay informational
    assert bench_compare.direction("extra.collection_sync_16metrics.collectives_per_sync") == "lower"
    assert bench_compare.direction("extra.collection_sync_16metrics.host_sync_coalesced_ms") == "lower"
    assert bench_compare.direction("extra.collection_sync_16metrics.leaves_coalesced_per_sync") is None
    assert bench_compare.direction("extra.collection_sync_16metrics.per_leaf_collectives") is None


def test_check_trips_on_per_leaf_collective_regression(tmp_path):
    """The acceptance gate: a round whose collection sync slid back toward
    per-leaf collectives (2 → 64 per sync) must trip ``--check`` even though
    every latency/throughput held steady."""
    sync_cfg = lambda colls: {"collection_sync_16metrics": {
        "collectives_per_sync": colls, "leaves_coalesced_per_sync": 64,
        "per_leaf_collectives": 64, "host_sync_coalesced_ms": 12.0,
    }}
    good = _round(1, 29500.0, extra_overrides=sync_cfg(2.0))
    bad = _round(2, 29500.0, extra_overrides=sync_cfg(64.0))
    paths = _write_rounds(tmp_path, [good, bad])
    report = bench_compare.compare_rounds(paths)
    regressed = [
        r["metric"] for tr in report["transitions"] for r in tr["rows"] if r["verdict"] == "regression"
    ]
    assert "extra.collection_sync_16metrics.collectives_per_sync" in regressed
    assert report["verdict"] == "regression"
    # and a steady coalesced round passes
    (tmp_path / "ok").mkdir()
    steady = _write_rounds(tmp_path / "ok", [good, _round(2, 29500.0, extra_overrides=sync_cfg(2.0))])
    report_ok = bench_compare.compare_rounds(steady)
    assert report_ok["verdict"] == "ok"


def test_regression_and_improvement_classification(tmp_path):
    prev = bench_compare.extract_metrics(_round(1, 30000.0))
    cur = bench_compare.extract_metrics(_round(2, 14000.0, psum_ms=1.9))  # -53% headline
    rows = {r["metric"]: r for r in bench_compare.compare_metrics(prev, cur)}
    assert rows["value"]["verdict"] == "regression"
    assert rows["vs_baseline"]["verdict"] == "regression"
    assert rows["extra.sync_allreduce_8dev_cpu.psum_latency_ms"]["verdict"] == "improved"
    assert rows["extra.fused_collection_cifar10.updates_per_sec"]["verdict"] == "ok"


def test_latency_increase_regresses_throughput_untouched():
    prev = bench_compare.extract_metrics(_round(1, 30000.0, psum_ms=2.0))
    cur = bench_compare.extract_metrics(_round(2, 30000.0, psum_ms=4.5))  # +125% latency
    rows = {r["metric"]: r for r in bench_compare.compare_metrics(prev, cur)}
    assert rows["extra.sync_allreduce_8dev_cpu.psum_latency_ms"]["verdict"] == "regression"
    assert rows["value"]["verdict"] == "ok"


def test_missing_config_reported_but_not_gated(tmp_path):
    """A config that errored in the newer round must not trip the default
    gate — bench's retry layer already owns that failure mode."""
    healthy = _round(1, 30000.0)
    errored = _round(2, 30000.0)
    errored["parsed"]["extra"]["coco_map_synthetic"] = {"error": "TimeoutExpired: ..."}
    paths = _write_rounds(tmp_path, [healthy, errored])
    report = bench_compare.compare_rounds(paths)
    rows = {r["metric"]: r for r in report["transitions"][0]["rows"]}
    assert rows["extra.coco_map_synthetic.images_per_sec_update"]["verdict"] == "missing"
    assert report["verdict"] == "ok"


def test_strict_missing_gates_dropped_configs(tmp_path):
    """--strict-missing (PR 6 satellite): a config silently dropped from the
    newer round is listed in every report and, under --check --strict-missing,
    fails the gate that would otherwise say 'no regressions'."""
    healthy = _round(1, 30000.0)
    errored = _round(2, 30000.0)
    errored["parsed"]["extra"]["coco_map_synthetic"] = {"error": "TimeoutExpired: ..."}
    paths = _write_rounds(tmp_path, [healthy, errored])
    report = bench_compare.compare_rounds(paths)
    assert report["missing"] == 2
    assert set(report["transitions"][0]["missing"]) == {
        "extra.coco_map_synthetic.images_per_sec_update",
        "extra.coco_map_synthetic.compute_sec_500imgs_80cls",
    }
    # the default text report lists the dropped metrics by name
    text = bench_compare.render_report(report)
    assert "missing from" in text and "images_per_sec_update" in text
    # default gate: passes; strict gate: fails; strict with nothing missing: passes
    assert bench_compare.main(paths + ["--check"]) == 0
    assert bench_compare.main(paths + ["--check", "--strict-missing"]) == 1
    same_dir = tmp_path / "same"
    same_dir.mkdir()
    same = _write_rounds(same_dir, [healthy, _round(2, 30000.0)])
    assert bench_compare.main(same + ["--check", "--strict-missing"]) == 0


def test_fid_missing_is_expected_known_and_never_gates(tmp_path):
    """ISSUE 12 bench hygiene: the fid probe's known transient in-pod failure
    (ROADMAP) is an expected-known row — reported with its reason on its own
    informational line, excluded from the missing count, and never gated,
    not even under --strict-missing. Returning columns report as 'new'."""
    healthy = _round(1, 30000.0)
    errored = _round(2, 30000.0)
    errored["parsed"]["extra"]["fid_inception_fwd"] = {
        "error": "INTERNAL: remote_compile: ...", "transient": True,
    }
    paths = _write_rounds(tmp_path, [healthy, errored])
    report = bench_compare.compare_rounds(paths)
    rows = {r["metric"]: r for r in report["transitions"][0]["rows"]}
    row = rows["extra.fid_inception_fwd.images_per_sec_bfloat16"]
    assert row["verdict"] == "known_missing"
    assert "remote_compile" in row["reason"]
    assert report["missing"] == 0
    assert report["transitions"][0]["known_missing"] == [
        "extra.fid_inception_fwd.images_per_sec_bfloat16"
    ]
    text = bench_compare.render_report(report)
    assert "expected-known missing" in text and "never gated" in text
    assert bench_compare.main(paths + ["--check", "--strict-missing"]) == 0
    # the verdict block bench.py embeds mirrors the classification
    verdict = bench_compare.verdict_against_previous(healthy["parsed"], errored["parsed"])
    assert verdict["missing"] == []
    assert verdict["known_missing"] == ["extra.fid_inception_fwd.images_per_sec_bfloat16"]
    # a round where fid lands again reports the column as returning
    back_dir = tmp_path / "back"
    back_dir.mkdir()
    back = _write_rounds(back_dir, [errored, _round(3, 30000.0)])
    report2 = bench_compare.compare_rounds(back)
    rows2 = {r["metric"]: r for r in report2["transitions"][0]["rows"]}
    assert rows2["extra.fid_inception_fwd.images_per_sec_bfloat16"]["verdict"] == "new"


def test_streaming_window_100k_directions():
    """Direction markers for the tiered-window bench columns: memory ratio
    and fresh-compile proof gate lower-exact, the serving ratio higher-exact,
    throughputs by the per_sec marker, workload constants informational."""
    d = bench_compare.direction
    assert d("extra.streaming_window_100k.dual_updates_per_sec_100k") == "higher"
    assert d("extra.streaming_window_100k.windowed_tenants_per_sec_1k") == "higher"
    assert d("extra.streaming_window_100k.windowed_serving_ratio") == "higher"
    assert d("extra.streaming_window_100k.state_memory_bytes_100k") == "lower"
    assert d("extra.streaming_window_100k.dual_mem_window_ratio") == "lower"
    assert d("extra.streaming_window_100k.vwupdate_fresh_compiles") == "lower"
    assert d("extra.streaming_window_100k.ring_window") is None
    assert d("extra.streaming_window_100k.ring_state_memory_bytes") is None
    assert d("extra.streaming_window_100k.windowed_rows_recorded") is None
    # the deterministic columns carry tight built-in thresholds
    assert bench_compare.THRESHOLDS["extra.streaming_window_100k.dual_mem_window_ratio"] <= 0.01
    # an injected memory-invariant break trips the gate
    rows = bench_compare.compare_metrics(
        {"extra.streaming_window_100k.dual_mem_window_ratio": 1.0},
        {"extra.streaming_window_100k.dual_mem_window_ratio": 4.0},
    )
    assert rows[0]["verdict"] == "regression"


def test_ttfu_columns_direction_and_gate(tmp_path):
    """time_to_first_update columns (AOT warm start): cold/warm gate in the
    lower direction, the speedup ratio in the higher direction — a warm path
    that silently falls back to compiling trips --check."""
    assert bench_compare.direction("extra.time_to_first_update_cold_s") == "lower"
    assert bench_compare.direction("extra.time_to_first_update_warm_s") == "lower"
    assert bench_compare.direction("extra.ttfu_warm_speedup_x") == "higher"
    assert bench_compare.direction("extra.ttfu_precompiled_programs") is None
    good = _round(1, 30000.0, extra_overrides={
        "time_to_first_update_cold_s": 0.25, "time_to_first_update_warm_s": 0.03,
        "ttfu_warm_speedup_x": 8.3,
    })
    # the warm path regressing to ~cold (a silently broken cache) must gate
    broken = _round(2, 30000.0, extra_overrides={
        "time_to_first_update_cold_s": 0.25, "time_to_first_update_warm_s": 0.24,
        "ttfu_warm_speedup_x": 1.04,
    })
    paths = _write_rounds(tmp_path, [good, broken])
    report = bench_compare.compare_rounds(paths)
    reg = {r["metric"] for t in report["transitions"] for r in t["rows"] if r["verdict"] == "regression"}
    assert "extra.time_to_first_update_warm_s" in reg
    assert "extra.ttfu_warm_speedup_x" in reg
    # ordinary shared-pod wobble stays inside the thresholds
    wobble = _round(2, 30000.0, extra_overrides={
        "time_to_first_update_cold_s": 0.31, "time_to_first_update_warm_s": 0.035,
        "ttfu_warm_speedup_x": 8.9,
    })
    wobble_dir = tmp_path / "wobble"
    wobble_dir.mkdir()
    paths = _write_rounds(wobble_dir, [good, wobble])
    report = bench_compare.compare_rounds(paths)
    assert report["verdict"] == "ok" and report["missing"] == 0


def test_device_map_and_embedder_columns_direction_and_gate(tmp_path):
    """Re-homed evaluator columns (device mAP + embedder pipelines): the device
    compute latencies gate lower, map_parity gates higher-exact (1.0-or-broken
    vs the host oracle), map_fresh_compiles stays informational, the raw
    cold/steady embedder columns gate lower, and the retired clamped
    *_compile_sec columns report expected-known missing — never gated."""
    assert bench_compare.direction("extra.coco_map_synthetic.device_compute_sec_5000imgs_80cls") == "lower"
    assert bench_compare.direction("extra.coco_map_synthetic.device_compute_cold_sec_5000imgs_80cls") == "lower"
    assert bench_compare.direction("extra.coco_map_synthetic.device_images_per_sec_update") == "higher"
    assert bench_compare.direction("extra.coco_map_synthetic.map_parity") == "higher"
    assert bench_compare.direction("extra.coco_map_synthetic.map_fresh_compiles") is None
    assert bench_compare.direction("extra.bertscore_clipscore.bertscore_cold_call_sec") == "lower"
    assert bench_compare.direction("extra.bertscore_clipscore.bertscore_steady_state_sec") == "lower"
    assert bench_compare.direction("extra.bertscore_clipscore.clipscore_cold_call_sec") == "lower"
    assert bench_compare.direction("extra.bertscore_clipscore.clipscore_steady_state_sec") == "lower"

    def cfg(dev_warm, parity, compiles, clip_cold):
        return {
            "coco_map_synthetic": {
                "images_per_sec_update": 106000.0, "compute_sec_5000imgs_80cls": 2.2,
                "device_images_per_sec_update": 10000.0,
                "device_compute_cold_sec_5000imgs_80cls": 4.4,
                "device_compute_sec_5000imgs_80cls": dev_warm,
                "map_parity": parity, "map_fresh_compiles": compiles,
            },
            "bertscore_clipscore": {
                "bertscore_pairs_per_sec_toy_embedder": 38000.0,
                "bertscore_cold_call_sec": 0.25, "bertscore_steady_state_sec": 0.007,
                "clipscore_pairs_per_sec_toy_embedder": 3500.0,
                "clipscore_cold_call_sec": clip_cold, "clipscore_steady_state_sec": 0.07,
            },
        }

    good = _round(1, 30000.0, extra_overrides=cfg(0.5, 1.0, 1, 0.3))
    # injected regressions: warm device compute sliding back to host speed, a
    # parity break against the oracle, a compile-count blowup (info only), and
    # a cold-call compile regression the old clamp could have hidden as 0.0
    broken = _round(2, 30000.0, extra_overrides=cfg(2.9, 0.0, 4, 3.5))
    paths = _write_rounds(tmp_path, [good, broken])
    report = bench_compare.compare_rounds(paths)
    rows = {r["metric"]: r for r in report["transitions"][0]["rows"]}
    reg = {m for m, r in rows.items() if r["verdict"] == "regression"}
    assert "extra.coco_map_synthetic.device_compute_sec_5000imgs_80cls" in reg
    assert "extra.coco_map_synthetic.map_parity" in reg
    assert "extra.bertscore_clipscore.clipscore_cold_call_sec" in reg
    assert rows["extra.coco_map_synthetic.map_fresh_compiles"]["verdict"] == "info"
    # ordinary shared-pod wobble stays inside the thresholds
    wobble_dir = tmp_path / "wobble"
    wobble_dir.mkdir()
    wobble = _round(2, 30000.0, extra_overrides=cfg(0.58, 1.0, 1, 0.41))
    paths = _write_rounds(wobble_dir, [good, wobble])
    assert bench_compare.compare_rounds(paths)["verdict"] == "ok"
    # the retired clamped columns: an old round that still reports them vs a
    # new round on the raw pair — expected-known missing, never gated
    retired_dir = tmp_path / "retired"
    retired_dir.mkdir()
    old_cfg = cfg(0.5, 1.0, 1, 0.3)
    old_cfg["bertscore_clipscore"]["bertscore_compile_sec"] = 6.69
    old_cfg["bertscore_clipscore"]["clipscore_compile_sec"] = 11.35
    old = _round(1, 30000.0, extra_overrides=old_cfg)
    paths = _write_rounds(retired_dir, [old, _round(2, 30000.0, extra_overrides=cfg(0.5, 1.0, 1, 0.3))])
    report = bench_compare.compare_rounds(paths)
    assert report["verdict"] == "ok" and report["missing"] == 0
    assert set(report["transitions"][0]["known_missing"]) == {
        "extra.bertscore_clipscore.bertscore_compile_sec",
        "extra.bertscore_clipscore.clipscore_compile_sec",
    }
    assert bench_compare.main(paths + ["--check", "--strict-missing"]) == 0


def test_production_soak_columns_direction_and_gate(tmp_path):
    """production_soak columns (chaos plane): shed_rate gates lower-exact,
    the recovery/reconciliation/determinism parities and recovered_faults
    gate higher-exact, latencies gate lower; the raw fault tallies are
    info-only (they'd hit the ``old == 0`` info short-circuit anyway — the
    zero-unrecovered invariant is gated through soak_recovery_parity)."""
    assert bench_compare.direction("extra.production_soak.shed_rate") == "lower"
    assert bench_compare.direction("extra.production_soak.recovered_faults") == "higher"
    assert bench_compare.direction("extra.production_soak.soak_recovery_parity") == "higher"
    assert bench_compare.direction("extra.production_soak.reconciliation_parity") == "higher"
    assert bench_compare.direction("extra.production_soak.soak_determinism_parity") == "higher"
    assert bench_compare.direction("extra.production_soak.update_p99_us") == "lower"
    assert bench_compare.direction("extra.production_soak.tenants_per_sec") == "higher"
    assert bench_compare.direction("extra.production_soak.faults_injected") is None
    assert bench_compare.direction("extra.production_soak.unrecovered_faults") is None

    def soak(shed_rate, recovery=1.0, determinism=1.0, p99=900.0):
        return {"production_soak": {
            "tenants_per_sec": 5200.0, "update_p50_us": 450.0, "update_p99_us": p99,
            "shed_rate": shed_rate, "events": 322, "faults_injected": 8,
            "recovered_faults": 6, "quarantined_faults": 1,
            "unrecovered_faults": 0 if recovery == 1.0 else 1,
            "soak_recovery_parity": recovery, "reconciliation_parity": 1.0,
            "soak_determinism_parity": determinism, "slo_breaches": 2,
            "spills": 7, "readmissions": 3, "unit": "tenant rows/s",
        }}

    good = _round(1, 30000.0, extra_overrides=soak(0.09))
    # an admission plane shedding 2.8x more of the same traffic must gate
    shedding = _round(2, 30000.0, extra_overrides=soak(0.25))
    paths = _write_rounds(tmp_path, [good, shedding])
    report = bench_compare.compare_rounds(paths)
    reg = {r["metric"] for t in report["transitions"] for r in t["rows"] if r["verdict"] == "regression"}
    assert "extra.production_soak.shed_rate" in reg
    assert bench_compare.main(paths + ["--check"]) == 1
    # a fault going unrecovered (parity 1.0 -> 0.0) gates even though the raw
    # unrecovered count is info-only (0 -> 1 would short-circuit to "info")
    broken_dir = tmp_path / "unrecovered"
    broken_dir.mkdir()
    paths = _write_rounds(broken_dir, [good, _round(2, 30000.0, extra_overrides=soak(0.09, recovery=0.0))])
    report = bench_compare.compare_rounds(paths)
    reg = {r["metric"] for t in report["transitions"] for r in t["rows"] if r["verdict"] == "regression"}
    assert "extra.production_soak.soak_recovery_parity" in reg
    assert bench_compare.main(paths + ["--check"]) == 1
    # a nondeterministic rerun (determinism parity 1.0 -> 0.0) gates too
    nondet_dir = tmp_path / "nondet"
    nondet_dir.mkdir()
    paths = _write_rounds(nondet_dir, [good, _round(2, 30000.0, extra_overrides=soak(0.09, determinism=0.0))])
    assert bench_compare.main(paths + ["--check"]) == 1
    # identical soak columns ride through clean
    steady_dir = tmp_path / "steady"
    steady_dir.mkdir()
    paths = _write_rounds(steady_dir, [good, _round(2, 30000.0, extra_overrides=soak(0.09))])
    report = bench_compare.compare_rounds(paths)
    assert report["verdict"] == "ok"
    assert bench_compare.main(paths + ["--check"]) == 0


def test_durable_failover_columns_direction_and_gate(tmp_path):
    """durable_failover columns (durability plane): the three parities and
    recovery_parity gate higher-exact (a torn snapshot or lost journal tail
    shows up as failover_state_parity/recovery_parity 1.0 -> 0.0), RPO gates
    lower-exact, RTO as an ordinary latency; the journal/snapshot tallies are
    info-only."""
    assert bench_compare.direction("extra.durable_failover.failover_state_parity") == "higher"
    assert bench_compare.direction("extra.durable_failover.recovery_parity") == "higher"
    assert bench_compare.direction("extra.durable_failover.degraded_sync_parity") == "higher"
    assert bench_compare.direction("extra.durable_failover.failover_rpo_records") == "lower"
    assert bench_compare.direction("extra.durable_failover.failover_rto_ms") == "lower"
    assert bench_compare.direction("extra.durable_failover.journal_records") is None
    assert bench_compare.direction("extra.durable_failover.snapshots") is None

    def failover(state_parity=1.0, recovery=1.0, rpo=0):
        return {"durable_failover": {
            "tenants_per_sec": 86.0, "failover_rto_ms": 1300.0,
            "failover_rpo_records": rpo, "replayed_records": 43,
            "journal_records": 759, "journal_fsyncs": 759, "snapshots": 3,
            "snapshot_restores": 1, "degraded_syncs": 1, "rank_rejoins": 1,
            "faults_injected": 11, "recovered_faults": 9, "unrecovered_faults": 0,
            "failover_state_parity": state_parity, "degraded_sync_parity": 1.0,
            "recovery_parity": recovery, "soak_recovery_parity": 1.0,
            "unit": "seeded durable soak",
        }}

    good = _round(1, 30000.0, extra_overrides=failover())
    # a torn snapshot / diverged standby: bitwise parity 1.0 -> 0.0 must gate
    torn = _round(2, 30000.0, extra_overrides=failover(state_parity=0.0))
    paths = _write_rounds(tmp_path, [good, torn])
    report = bench_compare.compare_rounds(paths)
    reg = {r["metric"] for t in report["transitions"] for r in t["rows"] if r["verdict"] == "regression"}
    assert "extra.durable_failover.failover_state_parity" in reg
    assert bench_compare.main(paths + ["--check"]) == 1
    # journal loss against the reference run: recovery_parity gates the same way
    lost_dir = tmp_path / "lost"
    lost_dir.mkdir()
    paths = _write_rounds(lost_dir, [good, _round(2, 30000.0, extra_overrides=failover(recovery=0.0))])
    assert bench_compare.main(paths + ["--check"]) == 1
    # identical durable columns ride through clean
    steady_dir = tmp_path / "steady"
    steady_dir.mkdir()
    paths = _write_rounds(steady_dir, [good, _round(2, 30000.0, extra_overrides=failover())])
    report = bench_compare.compare_rounds(paths)
    assert report["verdict"] == "ok"
    assert bench_compare.main(paths + ["--check"]) == 0


def test_fleet_failover_columns_direction_and_gate(tmp_path):
    """fleet_failover columns (fleet plane): the three parities gate
    higher-exact (a lost batch, a tenant seated twice, or a nondeterministic
    counter block shows up as a 1.0 -> 0.0 drop), RPO and the double-count
    tally gate lower-exact, and the workload tallies — including the
    wall-clock migration_us, which the "_us" marker would otherwise pin
    lower — ride info-only."""
    assert bench_compare.direction("extra.fleet_failover.fleet_failover_parity") == "higher"
    assert bench_compare.direction("extra.fleet_failover.migration_parity") == "higher"
    assert bench_compare.direction("extra.fleet_failover.fleet_determinism_parity") == "higher"
    assert bench_compare.direction("extra.fleet_failover.failover_rpo_records") == "lower"
    assert bench_compare.direction("extra.fleet_failover.double_counted_batches") == "lower"
    assert bench_compare.direction("extra.fleet_failover.migration_us") is None
    assert bench_compare.direction("extra.fleet_failover.host_failovers") is None
    assert bench_compare.direction("extra.fleet_failover.tenant_migrations") is None
    assert bench_compare.direction("extra.fleet_failover.lease_expiries") is None
    assert bench_compare.direction("extra.fleet_failover.fleet_heartbeats") is None

    def fleet(parity=1.0, migration=1.0, determinism=1.0, double=0):
        return {"fleet_failover": {
            "events": 841, "hosts": 3, "hosts_joined": 1, "host_failovers": 1,
            "tenant_migrations": 8, "lease_expiries": 1, "fleet_heartbeats": 320,
            "adopted_tenants": 3, "parked_batches": 5, "replayed_records": 3,
            "migration_us": 97000.0, "failover_rpo_records": 0,
            "double_counted_batches": double, "faults_injected": 2,
            "recovered_faults": 2, "unrecovered_faults": 0,
            "fleet_failover_parity": parity, "migration_parity": migration,
            "fleet_determinism_parity": determinism, "soak_recovery_parity": 1.0,
            "unit": "seeded 3-host fleet soak",
        }}

    good = _round(1, 30000.0, extra_overrides=fleet())
    # a lost/double-folded batch: per-tenant parity 1.0 -> 0.0 must gate
    lost = _round(2, 30000.0, extra_overrides=fleet(parity=0.0))
    paths = _write_rounds(tmp_path, [good, lost])
    report = bench_compare.compare_rounds(paths)
    reg = {r["metric"] for t in report["transitions"] for r in t["rows"] if r["verdict"] == "regression"}
    assert "extra.fleet_failover.fleet_failover_parity" in reg
    assert bench_compare.main(paths + ["--check"]) == 1
    # a migration that did not land bitwise gates the same way
    mig_dir = tmp_path / "mig"
    mig_dir.mkdir()
    paths = _write_rounds(mig_dir, [good, _round(2, 30000.0, extra_overrides=fleet(migration=0.0))])
    assert bench_compare.main(paths + ["--check"]) == 1
    # a counter block that stopped replaying run-to-run gates too
    det_dir = tmp_path / "det"
    det_dir.mkdir()
    paths = _write_rounds(det_dir, [good, _round(2, 30000.0, extra_overrides=fleet(determinism=0.0))])
    assert bench_compare.main(paths + ["--check"]) == 1
    # identical fleet columns ride through clean
    steady_dir = tmp_path / "steady"
    steady_dir.mkdir()
    paths = _write_rounds(steady_dir, [good, _round(2, 30000.0, extra_overrides=fleet())])
    report = bench_compare.compare_rounds(paths)
    assert report["verdict"] == "ok"
    assert bench_compare.main(paths + ["--check"]) == 0


def test_per_metric_threshold_override():
    prev = bench_compare.extract_metrics(_round(1, 30000.0))
    cur = bench_compare.extract_metrics(_round(2, 27000.0))  # -10%
    rows = {r["metric"]: r for r in bench_compare.compare_metrics(prev, cur)}
    assert rows["value"]["verdict"] == "ok"  # inside the default 25%
    rows = {r["metric"]: r for r in bench_compare.compare_metrics(prev, cur, overrides={"value": 0.05})}
    assert rows["value"]["verdict"] == "regression"


def test_verdict_against_previous_block():
    prev, cur = _round(1, 30000.0), _round(2, 12000.0)
    out = bench_compare.verdict_against_previous(prev["parsed"], cur["parsed"])
    assert out["verdict"] == "regression"
    assert any(r["metric"] == "value" for r in out["regressions"])
    out = bench_compare.verdict_against_previous(prev["parsed"], _round(2, 29500.0)["parsed"])
    assert out["verdict"] == "ok" and out["regressions"] == []


def test_embedded_verdict_block_not_flattened():
    """The regression_vs_previous block a round embeds is comparison output —
    it must not become metrics that every later comparison chases."""
    doc = _round(2, 30000.0)
    doc["parsed"]["extra"]["regression_vs_previous"] = {
        "verdict": "ok", "improved": 3, "ok": 5,
        "regressions": [{"metric": "value", "old": 1.0, "new": 0.5, "delta_pct": -50.0}],
    }
    metrics = bench_compare.extract_metrics(doc)
    assert not any("regression_vs_previous" in name for name in metrics)
    rows = bench_compare.compare_metrics(bench_compare.extract_metrics(_round(1, 30000.0)), metrics)
    assert not any("regression_vs_previous" in r["metric"] for r in rows)


# -------------------------------------------------------------- CLI smoke


def _cli(args):
    return subprocess.run([sys.executable, *args], capture_output=True, text=True, timeout=120)


def test_cli_check_trips_on_injected_regression(tmp_path):
    """Acceptance: a mid-trajectory injected regression exits nonzero."""
    paths = _write_rounds(tmp_path, [
        _round(1, 29000.0), _round(2, 30000.0), _round(3, 15000.0), _round(4, 15200.0),
    ])
    res = _cli([BENCH_COMPARE, *paths, "--check"])
    assert res.returncode == 1, res.stdout + res.stderr
    assert "REGRESSION" in res.stdout and "value" in res.stdout
    # same trajectory without --check reports but exits zero
    assert _cli([BENCH_COMPARE, *paths]).returncode == 0


def test_cli_check_passes_real_history():
    """Acceptance: the repo's real r01→r05 trajectory passes the gate."""
    rounds = sorted(glob.glob(os.path.join(REPO, "BENCH_r0*.json")))
    assert len(rounds) >= 2, "expected the seeded BENCH_r0*.json history"
    res = _cli([BENCH_COMPARE, *rounds, "--check"])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "verdict: OK" in res.stdout


def test_cli_json_output_and_threshold_flags(tmp_path):
    paths = _write_rounds(tmp_path, [_round(1, 30000.0), _round(2, 27500.0)])
    res = _cli([BENCH_COMPARE, *paths, "--json"])
    report = json.loads(res.stdout)
    assert report["verdict"] == "ok" and len(report["transitions"]) == 1
    res = _cli([BENCH_COMPARE, *paths, "--check", "--threshold-for", "value=0.01"])
    assert res.returncode == 1


def test_cli_rejects_single_round(tmp_path):
    paths = _write_rounds(tmp_path, [_round(1, 30000.0)])
    res = _cli([BENCH_COMPARE, *paths])
    assert res.returncode == 2 and "at least two" in res.stderr


# -------------------------------------------- latency percentile columns gate


def test_latency_percentile_columns_direction_and_gate(tmp_path):
    """The health-plane bench columns (update_p50_us/update_p99_us/sync_p99_us)
    gate as latencies: a p99 blowup trips --check; absence in older rounds is
    'new', never a regression."""
    assert bench_compare.direction("extra.update_p99_us") == "lower"
    assert bench_compare.direction("extra.collection_sync_16metrics.sync_p99_us") == "lower"
    # registered thresholds exist for every emitted column
    for name in (
        "extra.update_p50_us", "extra.update_p99_us",
        "extra.collection_sync_16metrics.update_p50_us",
        "extra.collection_sync_16metrics.update_p99_us",
        "extra.collection_sync_16metrics.sync_p99_us",
    ):
        assert name in bench_compare.THRESHOLDS
    cols = lambda p99: {"update_p50_us": 450.0, "update_p99_us": p99,
                        "collection_sync_16metrics": {"sync_p99_us": 40000.0,
                                                      "collectives_per_sync": 2.0}}
    old = _round(1, 29500.0)  # pre-health-plane round: no latency columns
    good = _round(2, 29500.0, extra_overrides=cols(900.0))
    bad = _round(3, 29500.0, extra_overrides=cols(9000.0))  # 10x p99 blowup
    paths = _write_rounds(tmp_path, [old, good, bad])
    res = _cli([BENCH_COMPARE, *paths, "--check"])
    assert res.returncode == 1, res.stdout + res.stderr
    assert "update_p99_us" in res.stdout
    report = bench_compare.compare_rounds(paths)
    first = {r["metric"]: r for r in report["transitions"][0]["rows"]}
    assert first["extra.update_p99_us"]["verdict"] == "new"  # no history: no gate
    # steady columns pass
    (tmp_path / "ok").mkdir()
    steady = _write_rounds(tmp_path / "ok", [good, _round(3, 29500.0, extra_overrides=cols(980.0))])
    assert _cli([BENCH_COMPARE, *steady, "--check"]).returncode == 0


# -------------------------------------------- telemetry history columns gate


def test_telemetry_history_columns_direction_and_gate(tmp_path):
    """The telemetry_history bench columns gate their contract: the O(levels)
    memory ratio and the determinism/endpoint/burn-drill parities are
    higher-exact, query latencies gate as latencies, and the raw block/fold
    counts stay informational. An injected memory-ratio collapse AND a missed
    burn page each trip --check."""
    assert bench_compare.direction("extra.telemetry_history.history_mem_savings_x") == "higher"
    assert bench_compare.direction("extra.telemetry_history.history_determinism_parity") == "higher"
    assert bench_compare.direction("extra.telemetry_history.historyz_parity") == "higher"
    assert bench_compare.direction("extra.telemetry_history.burn_drill_parity") == "higher"
    assert bench_compare.direction("extra.telemetry_history.history_query_p50_us") == "lower"
    assert bench_compare.direction("extra.telemetry_history.history_query_p99_us") == "lower"
    # the raw counts carry no direction: retention tuning may legitimately
    # move them either way
    assert bench_compare.direction("extra.telemetry_history.history_blocks_retained") is None
    assert bench_compare.direction("extra.telemetry_history.history_folds") is None
    assert bench_compare.direction("extra.telemetry_history.burn_pages") is None
    assert bench_compare.direction("extra.telemetry_history.single_window_alerts") is None
    for name in (
        "extra.telemetry_history.history_mem_savings_x",
        "extra.telemetry_history.history_determinism_parity",
        "extra.telemetry_history.historyz_parity",
        "extra.telemetry_history.burn_drill_parity",
        "extra.telemetry_history.history_query_p50_us",
        "extra.telemetry_history.history_query_p99_us",
    ):
        assert name in bench_compare.THRESHOLDS
    cols = lambda savings, burn: {"telemetry_history": {
        "history_mem_savings_x": savings, "history_blocks_retained": 81.0,
        "history_folds": 2278.0, "history_determinism_parity": 1.0,
        "historyz_parity": 1.0, "history_query_p50_us": 25.0,
        "history_query_p99_us": 64.0, "burn_drill_parity": burn,
        "burn_pages": 1.0 if burn else 0.0, "single_window_alerts": 12.0,
    }}
    good = _round(1, 29500.0, extra_overrides=cols(44.4, 1.0))
    # regression A: retention degenerated toward the naive ring (44x → 4x)
    mem_bad = _round(2, 29500.0, extra_overrides=cols(4.0, 1.0))
    paths = _write_rounds(tmp_path, [good, mem_bad])
    res = _cli([BENCH_COMPARE, *paths, "--check"])
    assert res.returncode == 1, res.stdout + res.stderr
    assert "history_mem_savings_x" in res.stdout
    # regression B: the burn drill missed its page (parity 1.0 → 0.0)
    (tmp_path / "burn").mkdir()
    burn_bad = _round(2, 29500.0, extra_overrides=cols(44.4, 0.0))
    paths = _write_rounds(tmp_path / "burn", [good, burn_bad])
    res = _cli([BENCH_COMPARE, *paths, "--check"])
    assert res.returncode == 1, res.stdout + res.stderr
    assert "burn_drill_parity" in res.stdout
    # steady rounds pass (small mem-ratio jitter stays inside the threshold)
    (tmp_path / "ok").mkdir()
    steady = _write_rounds(
        tmp_path / "ok", [good, _round(2, 29500.0, extra_overrides=cols(44.0, 1.0))])
    assert _cli([BENCH_COMPARE, *steady, "--check"]).returncode == 0


# ------------------------------------------------- bench crash-report harden


BENCH = os.path.join(REPO, "bench.py")

# the exact mangled headline BENCH_r05 recorded for fid_inception_fwd — the
# whole collapsed crash text arrived as ONE " | "-joined line and the old
# extractor reported it (IndexError artifact + truncated JAX footer) verbatim
R05_FID_STDOUT = (
    "IndexError: list index out of range: jax.errors.JaxRuntimeError: INTERNAL: "
    "http://127.0.0.1:8083/remote_compile: read body: response body closed before "
    "all bytes were read | -------------------- | For simplicity, JAX has removed "
    "its interna"
)


class _Res:
    def __init__(self, stdout="", stderr=""):
        self.stdout, self.stderr = stdout, stderr


def test_crash_report_r05_fid_fixture():
    """Acceptance (satellite): the exact r05 stdout now yields the clean
    {"error": <root cause>, "transient": true} shape — innermost exception,
    no " | " soup, no secondary-IndexError artifact."""
    bench = _load(BENCH)
    out = bench._crash_report(_Res(stdout=R05_FID_STDOUT))
    assert out == {
        "error": "jax.errors.JaxRuntimeError: INTERNAL: http://127.0.0.1:8083/"
                 "remote_compile: read body: response body closed before all bytes were read",
        "transient": True,
    }


def test_crash_report_chained_traceback_prefers_root_cause():
    """A real chained traceback ends on the secondary IndexError; the headline
    must still be the transient root cause (and classify transient)."""
    bench = _load(BENCH)
    tb = (
        "Traceback (most recent call last):\n"
        '  File "bench.py", line 1, in probe\n'
        "jax.errors.JaxRuntimeError: INTERNAL: read body: response body closed "
        "before all bytes were read\n\n"
        "During handling of the above exception, another exception occurred:\n\n"
        "Traceback (most recent call last):\n"
        '  File "bench.py", line 2, in report\n'
        "IndexError: list index out of range\n"
    )
    out = bench._crash_report(_Res(stderr=tb))
    assert out["transient"] is True
    assert out["error"].startswith("jax.errors.JaxRuntimeError: INTERNAL:")


def test_crash_report_plain_cases_unchanged():
    bench = _load(BENCH)
    out = bench._crash_report(_Res(stderr="ValueError: operands could not be broadcast"))
    assert out == {"error": "ValueError: operands could not be broadcast", "transient": False}
    out = bench._crash_report(_Res())
    assert out == {"error": "subprocess produced no output", "transient": False}


# ------------------------------------------- trace_report percentile columns


def _hist_event(metric, kind, count, buckets, ts=9.0):
    return json.dumps({
        "kind": "hist", "metric": metric, "tag": kind, "timestamp": ts,
        "payload": {"count": count, "sum": 0, "buckets": buckets},
    })


def test_trace_report_cli_latency_percentile_columns(tmp_path):
    """Acceptance (satellite): hist events become per-metric p50/p99 columns
    joined onto the dispatch rows, plus a footer latency line."""
    trace = tmp_path / "t.jsonl"
    # 10 updates: 8 fast (~bucket 5: 32-64us) + 2 slow (~bucket 15: 32-65ms)
    trace.write_text("\n".join([
        _event("dispatch", "Acc#0", "update", 1.0, cache_hit=False, duration_s=0.0001),
        _event("dispatch", "Acc#0", "update", 2.0, cache_hit=True, duration_s=0.0001),
        _hist_event("Acc#0", "update", 10, {"5": 8, "15": 2}),
        _hist_event("Acc#0", "sync", 1, {"15": 1}),
        _hist_event("Acc#0", "sync_payload", 1, {"2": 1}),  # size kind: footer only
    ]) + "\n")
    res = _cli([TRACE_REPORT, str(trace), "--json"])
    assert res.returncode == 0, res.stderr
    report = json.loads(res.stdout)
    rows = {(r["metric"], r["phase"]): r for r in report["rows"]}
    update = rows[("Acc#0", "update")]
    # p50 inside bucket 5 (32-64us -> ms), p99 inside bucket 15 (32.8-65.5ms)
    assert 0.032 <= update["p50_ms"] <= 0.064
    assert 32.0 <= update["p99_ms"] <= 66.0
    sync_row = rows[("Acc#0", "sync")]  # hist-only key still gets a row
    assert 32.0 <= sync_row["p99_ms"] <= 66.0
    assert ("Acc#0", "sync_payload") not in rows  # size kinds never fake a phase row
    assert report["latency"]["update"]["count"] == 10
    assert report["latency"]["sync_payload"]["p99_bytes"] is not None
    # table rendering: new columns + footer line
    res = _cli([TRACE_REPORT, str(trace)])
    header = res.stdout.splitlines()[0]
    assert "p50_ms" in header and "p99_ms" in header
    assert "latency:" in res.stdout and "update p99" in res.stdout


def test_trace_report_without_hist_events_keeps_dash_columns(tmp_path):
    trace = tmp_path / "plain.jsonl"
    trace.write_text(_event("dispatch", "Acc#0", "update", 1.0, cache_hit=False) + "\n")
    res = _cli([TRACE_REPORT, str(trace), "--json"])
    report = json.loads(res.stdout)
    assert report["rows"][0]["p50_ms"] is None and report["latency"] == {}
    res = _cli([TRACE_REPORT, str(trace)])
    assert "latency:" not in res.stdout


# --------------------------------------------- multi-host trace_report CLI


def _event(kind, metric, tag, ts, **kw):
    return json.dumps({"kind": kind, "metric": metric, "tag": tag, "timestamp": ts, **kw})


def test_trace_report_cli_multi_host(tmp_path):
    """Two per-host traces: per-rank rows, sync payload footer, and the
    skip-bad-line tolerance for a trace truncated by preemption."""
    host0 = tmp_path / "host0.jsonl"
    host0.write_text("\n".join([
        _event("dispatch", "Acc#0", "update", 1.0, cache_hit=False, duration_s=0.5),
        _event("dispatch", "Acc#0", "update", 2.0, cache_hit=True, duration_s=0.25),
        _event("sync", "Acc#0", "sync", 3.0, payload={"payload_bytes": 128}),
    ]) + "\n")
    host1 = tmp_path / "host1.jsonl"
    host1.write_text("\n".join([
        _event("dispatch", "Acc#0", "update", 1.0, cache_hit=False),
        _event("sync", "Acc#0", "sync", 3.5, payload={"payload_bytes": 64}),
        '{"kind": "sync", "metric": "Acc#0", "truncat',  # preempted mid-write
    ]) + "\n")
    res = _cli([TRACE_REPORT, str(host0), str(host1)])
    assert res.returncode == 0, res.stderr
    assert "unparseable line skipped" in res.stderr
    assert "rank" in res.stdout.splitlines()[0]
    assert "syncs: 2 (192 payload bytes" in res.stdout  # footer now also totals collectives
    # machine-readable: one dispatch row per rank
    res = _cli([TRACE_REPORT, str(host0), str(host1), "--json"])
    report = json.loads(res.stdout)
    update_rows = [r for r in report["rows"] if r["phase"] == "update"]
    assert sorted(r["rank"] for r in update_rows) == [0, 1]
    assert report["totals"]["sync_payload_bytes"] == 192


def test_trace_report_cli_single_file_keeps_plain_shape(tmp_path):
    trace = tmp_path / "t.jsonl"
    trace.write_text(_event("dispatch", "Acc#0", "update", 1.0, cache_hit=False) + "\n")
    res = _cli([TRACE_REPORT, str(trace), "--json"])
    report = json.loads(res.stdout)
    assert report["multi_rank"] is False
    assert "rank" not in report["rows"][0]
    assert not res.stdout.startswith("rank")


def test_trace_report_ranks_sort_numerically(tmp_path):
    """A 12-host merge must order ranks 0..11, not lexicographically 0,1,10,11,2..."""
    trace_report = _load(TRACE_REPORT)
    events = []
    for rank in range(12):
        events.extend(trace_report.load_events(_write_trace(tmp_path, rank), rank=rank))
    report = trace_report.aggregate(events)
    assert [r["rank"] for r in report["rows"]] == list(range(12))


def _write_trace(tmp_path, rank):
    p = tmp_path / f"host{rank}.jsonl"
    p.write_text(_event("dispatch", "Acc#0", "update", 1.0) + "\n")
    return str(p)


def test_trace_report_cli_rank_labels(tmp_path):
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    a.write_text(_event("dispatch", "Acc#0", "update", 1.0) + "\n")
    b.write_text(_event("dispatch", "Acc#0", "update", 1.0) + "\n")
    res = _cli([TRACE_REPORT, str(a), str(b), "--rank", "host-a", "--rank", "host-b", "--json"])
    report = json.loads(res.stdout)
    assert sorted(r["rank"] for r in report["rows"]) == ["host-a", "host-b"]
    # digit labels coerce to ints: rank 2 orders before rank 10
    res = _cli([TRACE_REPORT, str(a), str(b), "--rank", "10", "--rank", "2", "--json"])
    assert [r["rank"] for r in json.loads(res.stdout)["rows"]] == [2, 10]


def test_serving_columns_direction_and_gate(tmp_path):
    """multi_tenant_serving columns: throughputs and the speedup gate higher,
    spill latency gates lower, the one-compile proof gates lower (a slide to
    per-tenant compiles is THE pathology), and the baseline's one-shot boot
    cost plus churn-move count stay informational."""
    assert bench_compare.direction("extra.multi_tenant_serving.tenants_per_sec_1k") == "higher"
    assert bench_compare.direction("extra.multi_tenant_serving.tenants_per_sec_8k") == "higher"
    assert bench_compare.direction("extra.multi_tenant_serving.vs_naive_speedup_1k") == "higher"
    assert bench_compare.direction("extra.multi_tenant_serving.tenant_spill_us") == "lower"
    assert bench_compare.direction("extra.multi_tenant_serving.vupdate_fresh_compiles") == "lower"
    assert bench_compare.direction("extra.multi_tenant_serving.naive_boot_ms_per_tenant") is None
    assert bench_compare.direction("extra.multi_tenant_serving.spill_moves") is None
    assert bench_compare.direction("extra.multi_tenant_serving.telemetry.tenants_per_dispatch") is None
    # outside a telemetry block the amortization ratio gates higher
    assert bench_compare.direction("tenants_per_dispatch") == "higher"

    good = _round(1, 30000.0, extra_overrides={"multi_tenant_serving": {
        "tenants_per_sec_1k": 60000.0, "tenants_per_sec_8k": 55000.0,
        "naive_tenants_per_sec": 5000.0, "vs_naive_speedup_1k": 12.0,
        "tenant_spill_us": 300.0, "vupdate_fresh_compiles": 1,
        "naive_boot_ms_per_tenant": 90.0, "spill_moves": 512,
    }})
    # an engine sliding back toward one-dispatch-per-tenant must trip --check
    broken = _round(2, 30000.0, extra_overrides={"multi_tenant_serving": {
        "tenants_per_sec_1k": 9000.0, "tenants_per_sec_8k": 8500.0,
        "naive_tenants_per_sec": 5000.0, "vs_naive_speedup_1k": 1.8,
        "tenant_spill_us": 2500.0, "vupdate_fresh_compiles": 100,
        "naive_boot_ms_per_tenant": 90.0, "spill_moves": 512,
    }})
    paths = _write_rounds(tmp_path, [good, broken])
    report = bench_compare.compare_rounds(paths)
    reg = {r["metric"] for t in report["transitions"] for r in t["rows"] if r["verdict"] == "regression"}
    assert "extra.multi_tenant_serving.tenants_per_sec_1k" in reg
    assert "extra.multi_tenant_serving.vs_naive_speedup_1k" in reg
    assert "extra.multi_tenant_serving.tenant_spill_us" in reg
    assert "extra.multi_tenant_serving.vupdate_fresh_compiles" in reg
    assert bench_compare.main(paths + ["--check"]) == 1
    # shared-pod wobble stays inside the thresholds
    wobble = _round(2, 30000.0, extra_overrides={"multi_tenant_serving": {
        "tenants_per_sec_1k": 48000.0, "tenants_per_sec_8k": 44000.0,
        "naive_tenants_per_sec": 5600.0, "vs_naive_speedup_1k": 8.6,
        "tenant_spill_us": 420.0, "vupdate_fresh_compiles": 1,
        "naive_boot_ms_per_tenant": 70.0, "spill_moves": 512,
    }})
    wobble_dir = tmp_path / "wobble"
    wobble_dir.mkdir()
    paths = _write_rounds(wobble_dir, [good, wobble])
    report = bench_compare.compare_rounds(paths)
    assert report["verdict"] == "ok" and report["missing"] == 0
