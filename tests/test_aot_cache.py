"""AOT compile cache + warm-start precompile plane (``torchmetrics_tpu/aot``).

Pins the PR's acceptance contracts:

- dispatch-key signature stability: permuted kwargs, weak-typed Python
  scalars, and equivalent ``ShapeDtypeStruct`` inputs map to ONE key (a key
  miss silently turns every warm start into a cold compile);
- counter reconciliation extended: ``jit_compiles + jit_cache_hits +
  aot_cache_hits == dispatches`` holds exactly, including under injected
  cache corruption (corrupt entry → miss → fresh compile, never an error);
- the ``jax.export`` vs ``jax.experimental.export`` version shim resolves on
  this runtime and round-trips a program (parity-pinned like the PR 4
  ``shard_map`` shim);
- warm starts load bitwise-identical programs: values match the jit path.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchmetrics_tpu as tm
from torchmetrics_tpu import MetricCollection, aot
from torchmetrics_tpu import observability as obs
from torchmetrics_tpu.aot import cache as aot_cache
from torchmetrics_tpu.aot import codecs, compat, keys
from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassF1Score
from torchmetrics_tpu.metric import HostMetric, Metric
from torchmetrics_tpu.parallel import mesh as par_mesh

pytestmark = pytest.mark.aot

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Weighted(Metric):
    """Tensor-state metric taking positional + keyword inputs (signature tests)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, x, *, weight=1.0, bias=0.0):
        return {"total": (x * weight + bias).sum()}

    def _compute(self, state):
        return state["total"]


class _HostSum(HostMetric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("s", default=np.zeros(()), dist_reduce_fx="sum")

    def _host_batch_state(self, x):
        return {"s": jnp.asarray(np.asarray(x).sum())}

    def _compute(self, state):
        return state["s"]


def _x(n=6):
    return jnp.asarray(np.arange(n, dtype=np.float32))


def _acc(ncls=5):
    return MulticlassAccuracy(num_classes=ncls, average="micro", validate_args=False)


def _batch(ncls=5, batch=128, seed=0):
    rng = np.random.default_rng(seed)
    preds = jnp.asarray(rng.normal(size=(batch, ncls)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, ncls, batch, dtype=np.int32))
    return preds, target


def _plane(tmp_path, **cfg):
    return aot.enable(config=aot.AotConfig(cache_dir=str(tmp_path / "cache"), **cfg))


# ------------------------------------------------------- signature stability


def test_signature_kwargs_commute():
    a = jnp.zeros((4, 3), jnp.float32)
    s1 = keys.dispatch_signature(((a,), {"weight": _x(4), "bias": _x(4)}))
    s2 = keys.dispatch_signature(((a,), dict(reversed(list({"weight": _x(4), "bias": _x(4)}.items())))))
    assert s1 == s2
    k1 = keys.cache_key(_Weighted(), "update", {}, ((a,), {"weight": _x(4), "bias": _x(4)}))
    k2 = keys.cache_key(_Weighted(), "update", {}, ((a,), {"bias": _x(4), "weight": _x(4)}))
    assert k1 == k2


def test_signature_weak_python_scalars_value_free():
    a = jnp.zeros((4,), jnp.float32)
    # different VALUES, same key — jit keys on type, not value
    assert keys.dispatch_signature(((a, 1.0), {})) == keys.dispatch_signature(((a, 2.5), {}))
    assert keys.dispatch_signature(((a, 3), {})) == keys.dispatch_signature(((a, 7), {}))
    # a python float and the weak f32 scalar jax traces it as are ONE key
    assert keys.dispatch_signature(((a, 1.0), {})) == keys.dispatch_signature(((a, jnp.asarray(1.0)), {}))
    # …but a STRONG f32 scalar is a different program, hence a different key
    assert keys.dispatch_signature(((a, 1.0), {})) != keys.dispatch_signature(
        ((a, jnp.asarray(1.0, jnp.float32)), {})
    )
    # int vs float scalars differ
    assert keys.dispatch_signature(((a, 1), {})) != keys.dispatch_signature(((a, 1.0), {}))


def test_signature_shapedtypestruct_equals_concrete():
    concrete = jnp.zeros((8, 3), jnp.float32)
    spec = jax.ShapeDtypeStruct((8, 3), jnp.float32)
    assert keys.dispatch_signature(((concrete,), {})) == keys.dispatch_signature(((spec,), {}))
    # numpy f64 canonicalizes to the f32 program jit would build
    np64 = np.zeros((8, 3), np.float64)
    assert keys.dispatch_signature(((np64,), {})) == keys.dispatch_signature(((concrete,), {}))
    # shape and dtype changes still miss
    assert keys.dispatch_signature(((concrete,), {})) != keys.dispatch_signature(
        ((jnp.zeros((8, 4), jnp.float32),), {})
    )
    assert keys.dispatch_signature(((concrete,), {})) != keys.dispatch_signature(
        ((jnp.zeros((8, 3), jnp.int32),), {})
    )


def test_structure_hash_separates_layouts():
    a, b = _x(4), _x(4)
    flat = ((a, b), {})
    nested = (((a, b),), {})
    # same leaves → same display signature (the counters' legacy view)…
    assert keys.dispatch_signature(flat) == keys.dispatch_signature(nested)
    # …but different calling conventions never share a cache entry
    assert keys.structure_hash(flat) != keys.structure_hash(nested)
    m = _Weighted()
    assert keys.cache_key(m, "update", {}, flat) != keys.cache_key(m, "update", {}, nested)


def test_memo_distinguishes_calling_conventions(tmp_path):
    """Two conventions that flatten to the same leaves (positional vs kwarg)
    must not share a memo slot: the second convention misses and compiles —
    it never receives the first convention's executable (which would
    TypeError on the dispatch path)."""
    _plane(tmp_path)
    x, w = _x(8), _x(8)

    class _TwoArg(Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

        def _batch_state(self, a, b=None):
            return {"total": (a * b).sum()}

        def _compute(self, state):
            return state["total"]

    m = _TwoArg()
    m.precompile(x, w)  # positional convention
    aot.disable()
    _plane(tmp_path)
    warm = _TwoArg()
    with obs.telemetry_session() as rec:
        warm.update(x, w)       # positional: served from cache
        warm.update(x, b=w)     # kwarg form: same leaves, different pytree
    c = rec.counters.snapshot().counts
    # the PLANE saw two distinct programs (one load, one probe+miss); the
    # counters key on the flat signature, so the second dispatch lands in the
    # jit_cache_hits bucket (the documented signature-novelty approximation)
    # — the identity still reconciles exactly
    assert c["aot_cache_hits"] == 1 and c["aot_cache_misses"] == 1
    assert c["jit_compiles"] + c["jit_cache_hits"] + c["aot_cache_hits"] == c["dispatches"] == 2
    ref = _TwoArg()
    aot.disable()
    ref.update(x, w)
    ref.update(x, b=w)
    assert np.array_equal(np.asarray(warm.compute()), np.asarray(ref.compute()))


def test_warm_service_new_shape_is_not_a_retrace_storm(tmp_path):
    """A service that precompiled many shapes is warm, not churning: retrace
    events and the sentinel fire only on actual recompiles beyond a key's
    first compile."""
    _plane(tmp_path)
    m = _acc()
    for n in (8, 16, 32, 64):
        m.precompile(*_batch(batch=n))
    aot.disable()
    _plane(tmp_path)
    warm = _acc()
    with obs.telemetry_session(obs.TelemetryConfig(retrace_warn_threshold=2)) as rec:
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("error")  # any sentinel warning fails the test
            for n in (8, 16, 32, 64):
                warm.update(*_batch(batch=n))   # four aot loads, zero compiles
            warm.update(*_batch(batch=128))     # ONE legitimate new-shape compile
    snap = rec.counters.snapshot()
    assert snap["aot_cache_hits"] == 4 and snap["jit_compiles"] == 1
    assert snap["retraces"] == 0                 # the key's FIRST compile
    assert rec.events_of("retrace") == ()


def test_metric_config_shapes_the_key():
    preds, target = _batch()
    inputs = ((preds, target), {})
    k_micro = keys.cache_key(_acc(), "update", {}, inputs)
    macro = MulticlassAccuracy(num_classes=5, average="macro", validate_args=False)
    top2 = MulticlassAccuracy(num_classes=5, average="micro", top_k=2, validate_args=False)
    assert keys.cache_key(macro, "update", {}, inputs) != k_micro
    assert keys.cache_key(top2, "update", {}, inputs) != k_micro
    # distinct instances of the SAME construction share the key (that is the
    # whole point: the cache outlives any one Python object)
    assert keys.cache_key(_acc(), "update", {}, inputs) == k_micro


def test_runtime_fingerprint_in_key(monkeypatch):
    preds, target = _batch()
    inputs = ((preds, target), {})
    k1 = keys.cache_key(_acc(), "update", {}, inputs)
    monkeypatch.setattr(par_mesh, "runtime_fingerprint", lambda mesh=None: "jax=9.9.9|backend=other")
    k2 = keys.cache_key(_acc(), "update", {}, inputs)
    assert k1 != k2
    monkeypatch.undo()
    real = par_mesh.runtime_fingerprint()
    assert "jax=" in real and "backend=" in real and "ndev=" in real


def test_package_version_is_a_coarse_invalidator(monkeypatch):
    """The class-bytecode digest only sees the class's OWN methods; the
    package version in the key guarantees a library upgrade misses even when
    a thin delegator's bytecode is unchanged."""
    preds, target = _batch()
    inputs = ((preds, target), {})
    k1 = keys.cache_key(_acc(), "update", {}, inputs)
    assert f"pkg={keys.package_version()}" in k1
    monkeypatch.setattr(keys, "package_version", lambda: "99.99.99")
    assert keys.cache_key(_acc(), "update", {}, inputs) != k1


def test_x64_mode_keys_in_runtime_fingerprint():
    fp = par_mesh.runtime_fingerprint()
    assert "x64=0" in fp  # the suite runs with x64 disabled
    # scalar tokens derive from the live canonicalization, not hardcoded names
    assert keys.dispatch_signature(((1.0,), {})).startswith(str(jax.dtypes.canonicalize_dtype(float)))


def test_device_array_config_is_uncacheable(tmp_path):
    """A config attribute holding a DEVICE array (baked-in constants) cannot
    be identified without a D2H read — such metrics must be uncacheable
    (permanent miss), never false-hittable across different constants."""

    class _Scaled(Metric):
        def __init__(self, scale, **kw):
            super().__init__(**kw)
            self.scale = scale  # a jax array: values are constant-folded into the program
            self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

        def _batch_state(self, x):
            return {"total": (x * self.scale).sum()}

        def _compute(self, state):
            return state["total"]

    with pytest.raises(keys.UnfingerprintableConfig):
        keys.metric_fingerprint(_Scaled(jnp.asarray([2.0])))
    plane = _plane(tmp_path)
    m = _Scaled(jnp.asarray([2.0]))
    report = m.precompile(_x(4))
    assert report["update"]["status"] == "skipped" and "uncacheable" in report["update"]["reason"]
    # dispatch with the plane active: jit path owns it — no error, no probe
    with obs.telemetry_session() as rec:
        m.update(_x(4))
    c = rec.counters.snapshot().counts
    assert c["jit_compiles"] == 1 and c["aot_cache_misses"] == 0 and c["aot_cache_hits"] == 0
    assert plane.stats["misses"] == 0
    # numpy constants stay cacheable — and different values get different keys
    k_np2 = keys.metric_fingerprint(_Scaled(np.asarray([2.0])))
    k_np9 = keys.metric_fingerprint(_Scaled(np.asarray([9.0])))
    assert k_np2 != k_np9


def test_precompile_with_placeholders_skips_value_validation(tmp_path):
    """Documented placeholder workflow: ShapeDtypeStruct examples precompile
    even on metrics whose validate_args path reads input VALUES — and the
    entry still warm-serves the real concrete batch."""
    _plane(tmp_path)
    m = MulticlassAccuracy(num_classes=5, average="micro")  # validate_args=True default
    report = m.precompile(
        jax.ShapeDtypeStruct((128, 5), jnp.float32), jax.ShapeDtypeStruct((128,), jnp.int32)
    )
    assert report["update"]["status"] == "written"
    aot.disable()
    _plane(tmp_path)
    warm = MulticlassAccuracy(num_classes=5, average="micro")
    preds, target = _batch()
    with obs.telemetry_session() as rec:
        warm.update(preds, target)
    assert rec.counters.snapshot()["aot_cache_hits"] == 1


def test_precompile_explicit_cache_dir_wins_over_active_plane(tmp_path):
    plane_a = _plane(tmp_path)
    dir_b = str(tmp_path / "bake-cache")
    preds, target = _batch()
    report = _acc().precompile(preds, target, cache_dir=dir_b)
    assert report["update"]["status"] == "written"
    assert plane_a.cache.scan()["entries"] == 0  # nothing leaked into the active plane
    assert aot.AotCache(dir_b).scan()["entries"] == 1


# ------------------------------------------------------------ cache container


def test_cache_put_get_roundtrip_and_scan(tmp_path):
    c = aot_cache.AotCache(str(tmp_path))
    path = c.put("key-1", {"a": b"payload-a", "b": b"payload-bb"}, {"tag": "update"})
    assert os.path.exists(path) and c.has("key-1")
    entry = c.get("key-1")
    assert entry.sections == {"a": b"payload-a", "b": b"payload-bb"}
    assert entry.meta == {"tag": "update"}
    assert c.get("other-key") is None
    report = c.scan()
    assert report["entries"] == 1 and report["undecodable"] == []
    # same-key rewrite is atomic last-wins
    c.put("key-1", {"a": b"v2"}, {})
    assert c.get("key-1").sections == {"a": b"v2"}
    assert c.clear() == 1 and c.get("key-1") is None


@pytest.mark.parametrize("corruption", ["truncate", "bitflip", "magic", "empty", "header"])
def test_cache_corruption_is_a_miss_never_an_error(tmp_path, corruption):
    c = aot_cache.AotCache(str(tmp_path))
    path = c.put("k", {"x": b"A" * 256}, {})
    raw = bytearray(open(path, "rb").read())
    if corruption == "truncate":
        raw = raw[: len(raw) // 2]
    elif corruption == "bitflip":
        raw[-10] ^= 0xFF  # payload bit rot → checksum mismatch
    elif corruption == "magic":
        raw[:4] = b"XXXX"
    elif corruption == "empty":
        raw = bytearray()
    elif corruption == "header":
        raw[len(aot_cache.MAGIC) + 4 : len(aot_cache.MAGIC) + 8] = b"\x00\x00\x00\x00"
    with open(path, "wb") as fh:
        fh.write(bytes(raw))
    assert c.get("k") is None
    report = c.scan()
    assert report["entries"] == 0 and len(report["undecodable"]) == 1


def test_cache_prune_tmp(tmp_path):
    c = aot_cache.AotCache(str(tmp_path))
    open(os.path.join(c.root, ".tmp-123-dead"), "wb").write(b"partial")
    assert c.prune_tmp() == 1
    assert not any(n.startswith(".tmp-") for n in os.listdir(c.root))


# ----------------------------------------------------------- export shim


def test_export_shim_parity_and_roundtrip():
    """The jax.export/jax.experimental.export shim resolves on this runtime
    and round-trips a program — mirrors the PR 4 shard_map shim pinning."""
    assert compat.export_available()
    mod = compat.export_module()
    assert hasattr(mod, "export") and hasattr(mod, "deserialize")
    # whichever module generation resolved, it IS one of the two known homes
    assert mod.__name__ in ("jax.export", "jax.experimental.export")
    jf = jax.jit(lambda x: x * 2.0)
    blob = codecs.encode_exported(jf, (jax.ShapeDtypeStruct((3,), jnp.float32),), {})
    loaded = codecs.decode_exported(blob)
    out = loaded(jnp.asarray([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(np.asarray(out), [2.0, 4.0, 6.0])


def test_exec_codec_roundtrip_preserves_values():
    # no donation: the plane caches undonated programs only (a deserialized
    # executable's aliasing is invisible to python-side donation bookkeeping)
    jf = jax.jit(lambda s, n, x: ({k: v + x.sum() for k, v in s.items()}, n + 1.0))
    avals = (
        {"t": jax.ShapeDtypeStruct((), jnp.float32)},
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((4,), jnp.float32),
    )
    compiled = jf.lower(*avals).compile()
    blob = codecs.encode_executable(compiled)
    loaded = codecs.decode_executable(blob)
    out = loaded({"t": jnp.asarray(1.0, jnp.float32)}, jnp.asarray(0.0, jnp.float32), _x(4))
    assert float(out[0]["t"]) == 7.0 and float(out[1]) == 1.0
    with pytest.raises(codecs.CodecError):
        codecs.decode_executable(b"not a payload")


# ----------------------------------------------- warm start through dispatch


def test_precompile_then_warm_dispatch_reconciles(tmp_path):
    """Acceptance core: populate → fresh metric serves its first update from
    the cache; compiles + jit_cache_hits + aot_cache_hits == dispatches."""
    _plane(tmp_path)
    preds, target = _batch()
    report = _acc().precompile(preds, target)
    assert report["update"]["status"] == "written"
    assert codecs.CODEC_EXEC in report["update"]["codecs"]

    aot.disable()
    plane = _plane(tmp_path)  # simulated reboot: new plane, same directory
    warm = _acc()
    with obs.telemetry_session() as rec:
        warm.update(preds, target)
        warm.update(preds, target)
        value = warm.compute()
    c = rec.counters.snapshot().counts
    assert c["dispatches"] == 2
    assert c["aot_cache_hits"] == 1 and c["jit_compiles"] == 0 and c["jit_cache_hits"] == 1
    assert c["jit_compiles"] + c["jit_cache_hits"] + c["aot_cache_hits"] == c["dispatches"]
    assert c["aot_cache_misses"] == 0 and c["aot_deserialize_us"] > 0
    assert plane.stats["loads"] == 1
    ev = rec.events_of("aot_load")
    assert len(ev) == 1 and ev[0].payload["codec"] == codecs.CODEC_EXEC and ev[0].payload["nbytes"] > 0
    # bitwise parity with the plain jit path
    cold = _acc()
    aot.disable()
    cold.update(preds, target)
    cold.update(preds, target)
    assert np.array_equal(np.asarray(value), np.asarray(cold.compute()))
    # per-tag attribution shows the aot hit
    with obs.telemetry_session() as rec2:
        aot.enable(config=aot.AotConfig(cache_dir=str(tmp_path / "cache")))
        m3 = _acc()
        m3.update(preds, target)
        tags = rec2.metric_summary(m3)["tags"]
    assert tags["update"]["aot_hits"] == 1 and tags["update"]["compiles"] == 0


def test_corrupt_entry_misses_and_reconciles(tmp_path):
    """Acceptance criterion verbatim: the reconciliation invariant holds
    exactly under injected cache corruption — corrupt entry → miss → fresh
    compile, no exception."""
    plane = _plane(tmp_path)
    preds, target = _batch()
    _acc().precompile(preds, target)
    (entry_file,) = [f for f in os.listdir(plane.cache.root) if f.endswith(".aot")]
    path = os.path.join(plane.cache.root, entry_file)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(path, "wb") as fh:
        fh.write(bytes(raw))

    aot.disable()
    plane = _plane(tmp_path)
    m = _acc()
    with obs.telemetry_session() as rec:
        m.update(preds, target)  # corrupt → miss → fresh compile, no raise
        m.update(preds, target)
        value = m.compute()
    c = rec.counters.snapshot().counts
    assert c["jit_compiles"] == 1 and c["aot_cache_hits"] == 0 and c["aot_cache_misses"] == 1
    assert c["jit_compiles"] + c["jit_cache_hits"] + c["aot_cache_hits"] == c["dispatches"] == 2
    assert plane.stats["corrupt"] == 1
    cold = _acc()
    cold.update(preds, target)
    cold.update(preds, target)
    assert np.array_equal(np.asarray(value), np.asarray(cold.compute()))


def test_warm_start_with_kwargs_and_scalars(tmp_path):
    _plane(tmp_path)
    x = _x(16)
    m = _Weighted()
    m.precompile(x, weight=2.0, bias=1.0)
    aot.disable()
    _plane(tmp_path)
    warm = _Weighted()
    with obs.telemetry_session() as rec:
        warm.update(x, weight=3.0, bias=0.5)  # different VALUES, same program
    c = rec.counters.snapshot().counts
    assert c["aot_cache_hits"] == 1 and c["jit_compiles"] == 0
    ref = _Weighted()
    aot.disable()
    ref.update(x, weight=3.0, bias=0.5)
    assert np.array_equal(np.asarray(warm.compute()), np.asarray(ref.compute()))


def test_forward_tag_precompiles_and_serves(tmp_path):
    _plane(tmp_path)
    preds, target = _batch()
    report = _acc().precompile(preds, target, tags=("update", "forward"))
    assert report["forward"]["status"] == "written"
    aot.disable()
    _plane(tmp_path)
    warm = _acc()
    with obs.telemetry_session() as rec:
        val = warm.forward(preds, target)
    c = rec.counters.snapshot().counts
    assert c["aot_cache_hits"] == 1 and c["jit_compiles"] == 0
    ref = _acc()
    aot.disable()
    assert np.array_equal(np.asarray(val), np.asarray(ref.forward(preds, target)))


def test_collection_precompile_warms_every_member(tmp_path):
    _plane(tmp_path)
    ncls = 10
    preds, target = _batch(ncls=ncls, batch=256)

    def build():
        return MetricCollection({
            "acc": MulticlassAccuracy(ncls, average="micro", validate_args=False),
            "f1": MulticlassF1Score(ncls, average="macro", validate_args=False),
        }, compute_groups=False)

    report = build().precompile(preds, target)
    assert all(rows["update"]["status"] == "written" for rows in report.values())
    aot.disable()
    _plane(tmp_path)
    warm = build()
    with obs.telemetry_session() as rec:
        warm.update(preds, target)
        values = warm.compute()
    c = rec.counters.snapshot().counts
    assert c["jit_compiles"] == 0 and c["aot_cache_hits"] == 2
    assert c["jit_compiles"] + c["jit_cache_hits"] + c["aot_cache_hits"] == c["dispatches"]
    ref = build()
    aot.disable()
    ref.update(preds, target)
    for k, v in ref.compute().items():
        assert np.array_equal(np.asarray(values[k]), np.asarray(v))


def test_second_member_instance_shares_entry(tmp_path):
    """Content addressing: N identically-configured instances → ONE entry."""
    plane = _plane(tmp_path)
    preds, target = _batch()
    _acc().precompile(preds, target)
    report = _acc().precompile(preds, target)
    assert report["update"]["status"] == "cached"
    assert plane.cache.scan()["entries"] == 1


def test_write_on_miss_self_warms(tmp_path):
    plane = _plane(tmp_path, write_on_miss=True)
    preds, target = _batch()
    m = _acc()
    with obs.telemetry_session() as rec:
        m.update(preds, target)  # miss → compile → write-through
    assert rec.counters.snapshot()["aot_cache_misses"] == 1
    assert plane.stats["writes"] == 1 and plane.cache.scan()["entries"] == 1
    aot.disable()
    _plane(tmp_path)
    warm = _acc()
    with obs.telemetry_session() as rec2:
        warm.update(preds, target)  # the NEXT boot is warm
    assert rec2.counters.snapshot()["aot_cache_hits"] == 1


def test_backend_without_exec_serialization_degrades_to_portable(tmp_path, monkeypatch):
    """A backend whose PJRT refuses executable serialization still warm-starts
    through the portable jax.export payload (skips trace+lowering; XLA
    recompiles at load) instead of failing precompile outright."""
    monkeypatch.setattr(
        codecs, "encode_executable",
        lambda compiled: (_ for _ in ()).throw(codecs.CodecError("backend refused")),
    )
    _plane(tmp_path)
    preds, target = _batch()
    report = _acc().precompile(preds, target)
    assert report["update"]["status"] == "written"
    assert report["update"]["codecs"] == [codecs.CODEC_HLO]
    monkeypatch.undo()
    aot.disable()
    _plane(tmp_path)
    warm = _acc()
    with obs.telemetry_session() as rec:
        warm.update(preds, target)
    c = rec.counters.snapshot().counts
    assert c["aot_cache_hits"] == 1 and c["jit_compiles"] == 0
    assert rec.events_of("aot_load")[0].payload["codec"] == codecs.CODEC_HLO


def test_placement_mismatch_demotes_to_jit_not_crash(tmp_path):
    """Input placement/sharding is invisible to the shape/dtype key: a loaded
    executable called with inputs on another device must demote to the jit
    path (cached programs never donate, so the inputs are intact) — never an
    exception on the dispatch path."""
    _plane(tmp_path)
    preds, target = _batch()
    _acc().precompile(preds, target)
    aot.disable()
    _plane(tmp_path)
    warm = _acc()
    dev1 = jax.devices()[1]
    p1, t1 = jax.device_put(preds, dev1), jax.device_put(target, dev1)
    with obs.telemetry_session() as rec:
        warm.update(p1, t1)  # placement mismatch → demote, no raise
        value = warm.compute()
    c = rec.counters.snapshot().counts
    # the jit path actually served it: counted as a compile, and the slot's
    # demotion registers as an aot miss — the identity stays exact
    assert c["jit_compiles"] == 1 and c["aot_cache_hits"] == 0 and c["aot_cache_misses"] == 1
    assert c["jit_compiles"] + c["jit_cache_hits"] + c["aot_cache_hits"] == c["dispatches"] == 1
    ref = _acc()
    aot.disable()
    ref.update(preds, target)
    assert np.array_equal(np.asarray(value), np.asarray(ref.compute()))


def test_stale_runtime_fingerprint_misses(tmp_path, monkeypatch):
    _plane(tmp_path)
    preds, target = _batch()
    _acc().precompile(preds, target)
    aot.disable()
    _plane(tmp_path)
    # an upgraded runtime generation must never load yesterday's executables
    monkeypatch.setattr(par_mesh, "runtime_fingerprint", lambda mesh=None: "jax=99.0|backend=tpu-v9")
    m = _acc()
    with obs.telemetry_session() as rec:
        m.update(preds, target)
    c = rec.counters.snapshot().counts
    assert c["aot_cache_hits"] == 0 and c["aot_cache_misses"] == 1 and c["jit_compiles"] == 1


def test_host_metric_precompile_skips_cleanly(tmp_path):
    _plane(tmp_path)
    report = _HostSum().precompile(_x())
    assert report["update"]["status"] == "skipped"
    # a heterogeneous collection stays total
    coll = MetricCollection({"host": _HostSum(), "acc": _acc()})
    rows = coll.precompile(*_batch())
    assert rows["host"]["update"]["status"] == "skipped"
    assert rows["acc"]["update"]["status"] in ("written", "cached")


def test_jit_disabled_metric_skips(tmp_path):
    _plane(tmp_path)
    m = MulticlassAccuracy(num_classes=5, average="micro", validate_args=False, jit=False)
    report = m.precompile(*_batch())
    assert report["update"]["status"] == "skipped"
    # and the dispatch path never consults the plane for it
    with obs.telemetry_session() as rec:
        m.update(*_batch())
    assert rec.counters.snapshot()["aot_cache_misses"] == 0


def test_memo_invalidation_on_set_dtype(tmp_path):
    _plane(tmp_path)
    preds, target = _batch()
    m = _acc()
    m.precompile(preds, target)
    assert m.__dict__.get("_aot_memo")
    m.set_dtype(jnp.bfloat16)
    assert not m.__dict__.get("_aot_memo")  # stale programs dropped with the jit cache
    clone = _acc()
    clone.precompile(preds, target)
    assert clone.clone().__dict__.get("_aot_memo", {}) == {}
    import pickle

    assert "_aot_memo" not in pickle.loads(pickle.dumps(clone)).__dict__


def test_plane_disabled_is_default_and_inert(monkeypatch):
    assert aot.active_plane() is None  # the conftest fixture guarantees no leak
    # with the plane disabled, the dispatch path must never reach the plane —
    # one module-attribute None-check is the whole overhead
    calls = []
    monkeypatch.setattr(aot.AotPlane, "lookup_dispatch", lambda *a, **k: calls.append(1))
    m = _acc()
    m.update(*_batch())
    assert calls == []


def test_aot_session_context_restores_previous():
    with aot.aot_session() as plane:
        assert aot.active_plane() is plane
        with aot.aot_session() as inner:
            assert aot.active_plane() is inner
        assert aot.active_plane() is plane
    assert aot.active_plane() is None


# --------------------------------------------------- health-plane integration


def test_aot_load_rides_fleet_histogram_vector(tmp_path):
    from torchmetrics_tpu.observability import histograms as H

    assert "aot_load" in H.FLEET_HISTOGRAM_KINDS
    _plane(tmp_path)
    preds, target = _batch()
    _acc().precompile(preds, target)
    aot.disable()
    _plane(tmp_path)
    m = _acc()
    with obs.telemetry_session() as rec:
        m.update(preds, target)
        vec = rec.histograms.fleet_vector()
    merged = H.aggregate_histograms([vec, vec])
    assert merged["aot_load"].count == 2  # exact fieldwise-sum merge
    assert rec.latency_summary()["aot_load"]["count"] == 1


def test_counters_record_dispatch_aot_semantics():
    """Unit pin of the extended invariant, including retrace accounting:
    aot-served signatures never count as retraces."""
    c = obs.Counters()
    # second return element counts the key's COMPILES (not signatures): with
    # no aot activity it equals the old distinct-signature count exactly
    assert c.record_dispatch("M#0.update", "f32(4,)", aot_loaded=True) == (True, 0)
    assert c.record_dispatch("M#0.update", "f32(4,)") == (False, 0)
    assert c.record_dispatch("M#0.update", "f32(5,)") == (True, 1)  # first COMPILE
    assert c.record_dispatch("M#0.update", "f32(6,)") == (True, 2)  # first retrace
    snap = c.snapshot()
    assert snap["aot_cache_hits"] == 1 and snap["jit_compiles"] == 2 and snap["jit_cache_hits"] == 1
    assert snap["retraces"] == 1
    assert snap["jit_compiles"] + snap["jit_cache_hits"] + snap["aot_cache_hits"] == snap["dispatches"]
    rec = snap.per_key["M#0.update"]
    assert rec["aot_hits"] == 1 and rec["compiles"] == 2
    # fleet merge carries the aot fields
    fleet = obs.aggregate_counters([snap, snap])
    assert fleet["aot_cache_hits"] == 2
    assert fleet.per_key["M#0.update"]["aot_hits"] == 2


# ----------------------------------------------------------------- tooling


def test_warm_cache_cli_populates_and_scans(tmp_path):
    cache_dir = str(tmp_path / "cli-cache")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "warm_cache.py"),
         "--cache-dir", cache_dir, "--set", "flagship", "--batch", "32"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    report = json.loads(res.stdout)
    assert report["sets"]["flagship"]["counts"]["written"] == 1
    res2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "warm_cache.py"),
         "--cache-dir", cache_dir, "--scan"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert res2.returncode == 0
    scan = json.loads(res2.stdout)
    assert scan["entries"] == 1 and scan["undecodable"] == []
    # the populated cache actually warm-starts a fresh metric in-process
    aot.enable(cache_dir)
    m = MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)
    preds = jnp.zeros((32, 5), jnp.float32)
    target = jnp.zeros((32,), jnp.int32)
    with obs.telemetry_session() as rec:
        m.update(preds, target)
    assert rec.counters.snapshot()["aot_cache_hits"] == 1


def test_bench_ttfu_specs_build():
    """The bench's time-to-first-update builders construct without updating
    (cheap smoke — the full trio runs real subprocesses in the bench)."""
    sys.path.insert(0, REPO)
    try:
        import bench

        for name in bench.TTFU_CONFIGS:
            obj, args = bench._ttfu_spec(name)
            assert hasattr(obj, "update") and isinstance(args, tuple)
        assert set(bench.TTFU_CONFIGS) <= set(bench.CONFIGS)
    finally:
        sys.path.remove(REPO)


# ------------------------------------------------- threaded prefetch (PR 9)


def test_collection_precompile_prefetch_overlaps_loads(tmp_path):
    """Second boot of a collection: precompile reports every member 'cached'
    AND deserializes the entries on a thread pool into the dispatch memos —
    the first real batch is then served without a single disk probe, and the
    report's wall clock documents the overlap vs the serial sum."""
    cache = str(tmp_path / "prefetch")
    ncls = 10
    preds = jnp.zeros((64, ncls), jnp.float32)
    target = jnp.zeros((64,), jnp.int32)

    def build():
        return MetricCollection({
            "acc": MulticlassAccuracy(ncls, average="micro", validate_args=False),
            "f1": MulticlassF1Score(ncls, average="macro", validate_args=False),
        }, compute_groups=False)

    aot.enable(cache)
    first = build().precompile(preds, target)
    assert "_prefetch" not in first  # fresh writes are already primed in-process
    aot.disable()

    aot.enable(cache)
    coll = build()
    report = coll.precompile(preds, target)
    pf = report["_prefetch"]
    assert pf["loaded"] == 2
    assert pf["serial_load_s"] >= 0 and pf["wall_s"] >= 0
    assert all(rows["update"]["status"] == "loaded" for name, rows in pf["members"].items())
    with obs.telemetry_session() as rec:
        coll.update(preds, target)
    c = rec.counters.snapshot().counts
    # memo-primed loads: dispatches hit the prefetched executables, the
    # deserialize wall-clock still lands in the counter at first observation
    assert c["aot_cache_hits"] == 2 and c["jit_compiles"] == 0
    assert c["aot_deserialize_us"] > 0
    assert len(rec.events_of("aot_load")) == 2
    aot.disable()


def test_prefetch_compiled_miss_is_remembered(tmp_path):
    _plane(tmp_path)
    m = _acc()
    preds, target = _batch()
    report = m.prefetch_compiled(preds, target)
    assert report["update"]["status"] == "miss"
    with obs.telemetry_session() as rec:
        m.update(preds, target)  # remembered miss: jit path owns it, no re-probe
    c = rec.counters.snapshot().counts
    assert c["jit_compiles"] == 1 and c["aot_cache_hits"] == 0
    plane = aot.active_plane()
    assert plane.stats["misses"] == 1  # the prefetch probe, not the dispatch


def test_prefetch_compiled_host_metric_skips():
    aot.enable()
    try:
        report = _HostSum().prefetch_compiled(_x())
        assert report["update"]["status"] == "skipped"
    finally:
        aot.disable()


# --------------------------------------------- cache size budgeting (PR 9)


def test_cache_prune_lru_by_last_hit(tmp_path):
    """--max-bytes semantics: least-recently-hit entries (mtime order)
    evicted first, budget respected, undecodable files always reclaimed —
    and get() refreshes an entry's mtime so real loads ARE hits."""
    import time as _time

    plane = _plane(tmp_path)
    for n in (8, 16, 32, 64):
        _acc().precompile(*_batch(batch=n))
    scan = plane.cache.scan()
    assert scan["entries"] == 4 and scan["bytes"] > 0
    # a corrupt file is reclaimed unconditionally, whatever the budget
    bad = os.path.join(plane.cache.root, "deadbeef.aot")
    with open(bad, "wb") as fh:
        fh.write(b"not an entry")
    # get() stamps last-hit: an artificially ancient entry comes back fresh
    entry = next(plane.cache.entries())
    os.utime(entry.path, (1, 1))
    assert os.stat(entry.path).st_mtime < 100
    assert plane.cache.get(entry.key) is not None
    assert os.stat(entry.path).st_mtime > 100
    # explicit recency split: two cold entries, two hot survivors
    now = _time.time()
    entries = sorted(plane.cache.entries(), key=lambda e: e.path)
    cold, hot = entries[:2], entries[2:]
    for i, e in enumerate(cold):
        os.utime(e.path, (now - 1000 - i, now - 1000 - i))
    for e in hot:
        os.utime(e.path, (now, now))
    budget = sum(os.path.getsize(e.path) for e in hot)
    report = plane.cache.prune(budget)
    assert "deadbeef.aot" in report["removed"]
    assert report["kept_bytes"] <= budget
    left = {f for f in os.listdir(plane.cache.root) if f.endswith(".aot")}
    assert left == {os.path.basename(e.path) for e in hot}
    assert {os.path.basename(e.path) for e in cold} <= set(report["removed"])


def test_warm_cache_cli_max_bytes(tmp_path):
    cache_dir = str(tmp_path / "cli-prune")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "warm_cache.py"),
         "--cache-dir", cache_dir, "--set", "flagship", "--batch", "32"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "warm_cache.py"),
         "--cache-dir", cache_dir, "--max-bytes", "1K"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    report = json.loads(res.stdout)
    assert report["max_bytes"] == 1024
    assert report["scan"]["bytes"] <= 1024
    # suffix parsing is exact
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "warm_cache_t", os.path.join(REPO, "tools", "warm_cache.py"))
        wc = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(wc)
        assert wc.parse_size("512M") == 512 * 2**20
        assert wc.parse_size("2G") == 2 * 2**30
        assert wc.parse_size("65536") == 65536
        assert wc.parse_size("1KB") == 1024
    finally:
        sys.path.remove(os.path.join(REPO, "tools"))
