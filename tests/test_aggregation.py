"""Aggregator tests (reference tests/unittests/bases/test_aggregation.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu import CatMetric, MaxMetric, MeanMetric, MinMetric, RunningMean, RunningSum, SumMetric
from conftest import seed_all


def test_sum_metric():
    m = SumMetric()
    m.update(jnp.asarray([1.0, 2.0]))
    m.update(3.0)
    assert float(m.compute()) == 6.0


def test_mean_metric_weighted():
    m = MeanMetric()
    m.update(jnp.asarray([1.0, 3.0]))
    m.update(5.0, weight=2.0)
    # (1 + 3 + 5*2) / (1 + 1 + 2)
    assert float(m.compute()) == pytest.approx(14 / 4)


def test_max_min_metric():
    mx, mn = MaxMetric(), MinMetric()
    for v in ([1.0, 5.0], [3.0], [-2.0]):
        mx.update(jnp.asarray(v))
        mn.update(jnp.asarray(v))
    assert float(mx.compute()) == 5.0
    assert float(mn.compute()) == -2.0


def test_cat_metric():
    m = CatMetric()
    m.update(jnp.asarray([1.0, 2.0]))
    m.update(3.0)
    np.testing.assert_array_equal(np.asarray(m.compute()), [1.0, 2.0, 3.0])


def test_nan_error():
    m = SumMetric(nan_strategy="error")
    with pytest.raises(RuntimeError, match="nan"):
        m.update(jnp.asarray([1.0, jnp.nan]))


def test_nan_warn_ignores():
    m = SumMetric(nan_strategy="warn")
    with pytest.warns(UserWarning):
        m.update(jnp.asarray([1.0, jnp.nan, 2.0]))
    assert float(m.compute()) == 3.0


def test_nan_impute():
    m = SumMetric(nan_strategy=10.0)
    m.update(jnp.asarray([1.0, jnp.nan]))
    assert float(m.compute()) == 11.0


def test_nan_ignore_mean():
    m = MeanMetric(nan_strategy="ignore")
    m.update(jnp.asarray([2.0, jnp.nan, 4.0]))
    assert float(m.compute()) == 3.0


def test_running_mean_window():
    m = RunningMean(window=3)
    vals = [1.0, 2.0, 3.0, 4.0, 5.0]
    for v in vals:
        m.update(v)
    # last 3 batch means: 3, 4, 5
    assert float(m.compute()) == pytest.approx(4.0)


def test_running_sum_window():
    m = RunningSum(window=2)
    for v in ([1.0, 1.0], [2.0], [3.0]):
        m.update(jnp.asarray(v))
    # last 2 batch sums: 2, 3
    assert float(m.compute()) == 5.0


def test_running_partial_window():
    m = RunningMean(window=5)
    m.update(2.0)
    m.update(4.0)
    assert float(m.compute()) == 3.0


def test_aggregators_compose_in_collection():
    from torchmetrics_tpu import MetricCollection

    col = MetricCollection({"sum": SumMetric(), "mean": MeanMetric()}, compute_groups=False)
    col.update(jnp.asarray([2.0, 4.0]))
    out = col.compute()
    assert float(out["sum"]) == 6.0
    assert float(out["mean"]) == 3.0
