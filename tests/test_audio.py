"""Audio tower parity tests vs the reference oracle (pure-torch metrics; the
wheel-backed PESQ/STOI/DNSMOS/SRMR/NISQA are gated in both trees and tested for their
clear unavailable errors)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from tests.helpers import _assert_allclose
from tests.oracle import reference_torchmetrics

import torchmetrics_tpu as tm
import torchmetrics_tpu.functional as F

_RNG = np.random.default_rng(17)
PREDS = _RNG.normal(size=(2, 4, 256)).astype(np.float32)
TARGET = (0.8 * PREDS + 0.2 * _RNG.normal(size=(2, 4, 256))).astype(np.float32)


def _oracle():
    tm_ref = reference_torchmetrics()
    if tm_ref is None:
        pytest.skip("oracle unavailable")
    import torch

    return tm_ref, torch


SNR_CASES = [
    ("signal_noise_ratio", "SignalNoiseRatio", dict(zero_mean=True)),
    ("signal_noise_ratio", "SignalNoiseRatio", dict(zero_mean=False)),
    ("scale_invariant_signal_noise_ratio", "ScaleInvariantSignalNoiseRatio", dict()),
    ("scale_invariant_signal_distortion_ratio", "ScaleInvariantSignalDistortionRatio", dict(zero_mean=True)),
    ("source_aggregated_signal_distortion_ratio", "SourceAggregatedSignalDistortionRatio", dict()),
    ("source_aggregated_signal_distortion_ratio", "SourceAggregatedSignalDistortionRatio",
     dict(scale_invariant=False, zero_mean=True)),
]


@pytest.mark.parametrize("fn_name,cls_name,kwargs", SNR_CASES,
                         ids=[f"{c[0]}-{i}" for i, c in enumerate(SNR_CASES)])
def test_snr_family_parity(fn_name, cls_name, kwargs):
    tm_ref, torch = _oracle()
    ours = getattr(F, fn_name)(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), **kwargs)
    ref = getattr(tm_ref.functional.audio, fn_name)(torch.as_tensor(PREDS[0]), torch.as_tensor(TARGET[0]), **kwargs)
    _assert_allclose(ours, ref.numpy(), atol=1e-4)
    ours_m = getattr(tm, cls_name)(**kwargs)
    ref_m = getattr(tm_ref.audio, cls_name)(**kwargs)
    for i in range(2):
        ours_m.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
        ref_m.update(torch.as_tensor(PREDS[i]), torch.as_tensor(TARGET[i]))
    _assert_allclose(ours_m.compute(), ref_m.compute().numpy(), atol=1e-4)


def test_complex_si_snr_parity():
    tm_ref, torch = _oracle()
    preds = _RNG.normal(size=(1, 8, 10, 2)).astype(np.float32)
    target = (0.9 * preds + 0.1 * _RNG.normal(size=(1, 8, 10, 2))).astype(np.float32)
    ours = F.complex_scale_invariant_signal_noise_ratio(jnp.asarray(preds), jnp.asarray(target))
    ref = tm_ref.functional.audio.complex_scale_invariant_signal_noise_ratio(
        torch.as_tensor(preds), torch.as_tensor(target)
    )
    _assert_allclose(ours, ref.numpy(), atol=1e-4)


@pytest.mark.parametrize("zero_mean", [False, True])
def test_sdr_parity(zero_mean):
    tm_ref, torch = _oracle()
    # use a short filter for test speed; semantics identical
    ours = F.signal_distortion_ratio(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]),
                                     filter_length=64, zero_mean=zero_mean)
    ref = tm_ref.functional.audio.signal_distortion_ratio(
        torch.as_tensor(PREDS[0]), torch.as_tensor(TARGET[0]), filter_length=64, zero_mean=zero_mean
    )
    _assert_allclose(ours, ref.numpy(), atol=1e-3)
    ours_m = tm.SignalDistortionRatio(filter_length=64, zero_mean=zero_mean)
    ref_m = tm_ref.audio.SignalDistortionRatio(filter_length=64, zero_mean=zero_mean)
    for i in range(2):
        ours_m.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
        ref_m.update(torch.as_tensor(PREDS[i]), torch.as_tensor(TARGET[i]))
    _assert_allclose(ours_m.compute(), ref_m.compute().numpy(), atol=1e-3)


@pytest.mark.parametrize("mode", ["speaker-wise", "permutation-wise"])
@pytest.mark.parametrize("eval_func", ["max", "min"])
def test_pit_parity(mode, eval_func):
    tm_ref, torch = _oracle()
    preds = PREDS[:, :2]  # (batch, 2 speakers, time)
    target = TARGET[:, [1, 0]]  # permuted targets so PIT has work to do
    ours_metric, ours_perm = F.permutation_invariant_training(
        jnp.asarray(preds[0:1]), jnp.asarray(target[0:1]),
        F.scale_invariant_signal_distortion_ratio, mode=mode, eval_func=eval_func,
    )
    ref_metric, ref_perm = tm_ref.functional.audio.permutation_invariant_training(
        torch.as_tensor(preds[0:1]), torch.as_tensor(target[0:1]),
        tm_ref.functional.audio.scale_invariant_signal_distortion_ratio, mode=mode, eval_func=eval_func,
    )
    _assert_allclose(ours_metric, ref_metric.numpy(), atol=1e-4)
    assert np.array_equal(np.asarray(ours_perm), ref_perm.numpy())
    # permutate round-trip
    _assert_allclose(
        F.pit_permutate(jnp.asarray(preds[0:1]), ours_perm),
        tm_ref.functional.audio.pit_permutate(torch.as_tensor(preds[0:1]), ref_perm).numpy(),
        atol=1e-6,
    )


def test_pit_many_speakers_lsa_path():
    tm_ref, torch = _oracle()
    preds = _RNG.normal(size=(2, 5, 64)).astype(np.float32)  # 5 speakers -> LSA branch
    target = preds[:, ::-1].copy()
    ours_metric, ours_perm = F.permutation_invariant_training(
        jnp.asarray(preds), jnp.asarray(target), F.scale_invariant_signal_distortion_ratio
    )
    ref_metric, ref_perm = tm_ref.functional.audio.permutation_invariant_training(
        torch.as_tensor(preds), torch.as_tensor(target),
        tm_ref.functional.audio.scale_invariant_signal_distortion_ratio,
    )
    _assert_allclose(ours_metric, ref_metric.numpy(), atol=1e-4)
    assert np.array_equal(np.asarray(ours_perm), ref_perm.numpy())


def test_pit_class_matches_functional_mean():
    m = tm.PermutationInvariantTraining(F.scale_invariant_signal_distortion_ratio)
    for i in range(2):
        m.update(jnp.asarray(PREDS[i : i + 1, :2]), jnp.asarray(TARGET[i : i + 1, [1, 0]]))
    vals = [
        F.permutation_invariant_training(
            jnp.asarray(PREDS[i : i + 1, :2]), jnp.asarray(TARGET[i : i + 1, [1, 0]]),
            F.scale_invariant_signal_distortion_ratio,
        )[0]
        for i in range(2)
    ]
    _assert_allclose(m.compute(), np.mean([float(v[0]) for v in vals]), atol=1e-5)


def test_audio_merge_matches_single():
    single = tm.SignalNoiseRatio()
    shards = [tm.SignalNoiseRatio() for _ in range(2)]
    for i in range(2):
        single.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
        shards[i].update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
    shards[0].merge_state(shards[1])
    _assert_allclose(shards[0].compute(), single.compute(), atol=1e-6)


def test_gated_audio_metrics_raise_clearly():
    with pytest.raises(ModuleNotFoundError, match="pesq"):
        F.perceptual_evaluation_speech_quality(jnp.zeros(100), jnp.zeros(100), 8000, "nb")
    with pytest.raises(ModuleNotFoundError, match="pystoi"):
        F.short_time_objective_intelligibility(jnp.zeros(100), jnp.zeros(100), 8000)
    with pytest.raises(ModuleNotFoundError, match="pesq"):
        tm.PerceptualEvaluationSpeechQuality(8000, "nb")
    with pytest.raises(ModuleNotFoundError, match="pystoi"):
        tm.ShortTimeObjectiveIntelligibility(8000)
    # SRMR is now fully in-tree (no wheels needed); DNSMOS gates only on
    # onnxruntime (melspec is in-tree) unless infer_fns are injected
    with pytest.raises(ModuleNotFoundError, match="onnxruntime"):
        tm.DeepNoiseSuppressionMeanOpinionScore(16000, False)
    with pytest.raises(ModuleNotFoundError, match="NISQA checkpoint"):
        # explicit missing path: hermetic even when the user cache has the real tar
        tm.NonIntrusiveSpeechQualityAssessment(16000, checkpoint_path="/nonexistent/nisqa.tar")


def test_audio_validation_errors():
    with pytest.raises(RuntimeError, match="same shape"):
        F.signal_noise_ratio(jnp.zeros(10), jnp.zeros(12))
    with pytest.raises(RuntimeError, match="frequency, time, 2"):
        F.complex_scale_invariant_signal_noise_ratio(jnp.zeros((4, 10)), jnp.zeros((4, 10)))
    with pytest.raises(ValueError, match="eval_func"):
        F.permutation_invariant_training(
            jnp.zeros((1, 2, 8)), jnp.zeros((1, 2, 8)), F.scale_invariant_signal_distortion_ratio, eval_func="bad"
        )


def test_pit_class_many_speakers_no_crash():
    """Regression: the class path must work through the host scipy LSA branch."""
    preds = _RNG.normal(size=(2, 5, 64)).astype(np.float32)
    m = tm.PermutationInvariantTraining(F.scale_invariant_signal_distortion_ratio)
    m.update(jnp.asarray(preds), jnp.asarray(preds[:, ::-1].copy()))
    assert np.isfinite(float(m.compute()))
