"""Fused-collection (as_pure) fuzz: the one-XLA-program path must agree with the
stateful API on random metric subsets (VERDICT r4 weak #6 breadth: the fused
path was exercised on fixed 4-metric collections only).

Each trial samples 4-10 metrics from the compute-group pool, runs the same
batches through (a) the stateful MetricCollection and (b) `as_pure()` with a
jitted donated update, and requires name-for-name equality. An in-graph
8-device reduce over sharded per-device states closes the loop on plane 1 for
the fused path.
"""

from __future__ import annotations

import jax
from torchmetrics_tpu.parallel import shard_map as _shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from torchmetrics_tpu import MetricCollection

from conftest import seed_all
from test_compute_group_fuzz import POOL, _flatten

C = 5
N = 48


def _collection(names):
    return MetricCollection({n: POOL[n][0]() for n in names})


@pytest.mark.parametrize("trial", range(5))
def test_as_pure_matches_stateful(trial):
    rng = seed_all(8800 + trial)
    names = sorted(rng.choice(sorted(POOL), size=int(rng.integers(4, 11)), replace=False).tolist())
    batches = []
    for _ in range(3):
        logits = rng.normal(size=(N, C)).astype(np.float32)
        probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        batches.append((jnp.asarray(probs), jnp.asarray(rng.integers(0, C, N, dtype=np.int32))))

    stateful = _collection(names)
    for probs, target in batches:
        stateful.update(probs, target)
    want = {}
    for key, val in stateful.compute().items():
        _flatten(key, val, want)

    base = _collection(names)
    pure = base.as_pure()
    step = jax.jit(pure.update, donate_argnums=0)
    states = pure.init()
    for probs, target in batches:
        states = step(states, probs, target)
    # contract: compute jits iff every member's compute is device-traceable;
    # host-compute members (MCC's f64 edge cases) compute eagerly instead
    all_jittable = all(m._jittable_compute for m in base.values())
    compute = jax.jit(pure.compute) if all_jittable else pure.compute
    got = {}
    for key, val in compute(states).items():
        _flatten(key, val, got)

    assert got.keys() == want.keys()
    for key in want:
        np.testing.assert_allclose(got[key], want[key], atol=1e-6, err_msg=f"trial {trial}: {key}")


def test_host_compute_member_raises_clearly_under_jit():
    """Jitting pure.compute over a host-compute member (MCC's f64 edge handling)
    fails at trace time with actionable guidance, not a cryptic tracer error."""
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    pure = _collection(["acc_macro", "matthews"]).as_pure()
    states = pure.init()
    rng = seed_all(5)
    probs = np.exp(rng.normal(size=(N, C))).astype(np.float32)
    probs /= probs.sum(-1, keepdims=True)
    states = pure.update(states, jnp.asarray(probs), jnp.asarray(rng.integers(0, C, N, dtype=np.int32)))
    with pytest.raises(TorchMetricsUserError, match="OUTSIDE jit"):
        jax.jit(pure.compute)(states)
    # the eager path still computes everything
    vals = pure.compute(states)
    assert set(vals) == {"acc_macro", "matthews"}


def test_as_pure_mesh_reduce_matches_oneshot():
    """Per-device fused updates + one in-graph reduce == one-shot accumulation."""
    rng = seed_all(99)
    names = sorted(POOL)[:6]
    batches = []
    for _ in range(8):
        logits = rng.normal(size=(N, C)).astype(np.float32)
        probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        batches.append((jnp.asarray(probs), jnp.asarray(rng.integers(0, C, N, dtype=np.int32))))

    oneshot = _collection(names)
    for probs, target in batches:
        oneshot.update(probs, target)
    want = {}
    for key, val in oneshot.compute().items():
        _flatten(key, val, want)

    pure = _collection(names).as_pure()
    per_dev = [pure.update(pure.init(), *b) for b in batches]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_dev)
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    reduce_fn = jax.jit(_shard_map(
        lambda s: pure.reduce(jax.tree.map(lambda v: v[0], s), "dp"),
        mesh=mesh, in_specs=(P("dp"),), out_specs=P(), check_vma=False,
    ))
    reduced = reduce_fn(stacked)
    got = {}
    for key, val in jax.jit(pure.compute)(reduced).items():
        _flatten(key, val, got)
    for key in want:
        np.testing.assert_allclose(got[key], want[key], atol=1e-6, err_msg=key)
