"""Coalesced sync plane (ISSUE 5): bucketed single-collective synchronization.

Parity contract: for EVERY reduction tag (sum / mean / weighted-mean / max /
min / cat / custom callable), mixed dtypes including bf16, uneven cat shapes
across ranks, and zero-update ranks, the bucketed plane must produce results
**bitwise identical** to the per-leaf plane — the buckets only change the
transport, never the fold. Reliability: a faulty bucketed gather (FlakyGather)
must roll back to the last good state exactly like the per-leaf path.

Worlds are simulated through the ``dist_sync_fn`` injection seam with replay
fakes: the coalesced fake answers each collective with what every simulated
rank's ``build_local_metadata``/``build_bucket_payload`` would ship; the
per-leaf fake answers each leaf gather with every rank's prepared leaf.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchmetrics_tpu as tm
from torchmetrics_tpu import MetricCollection, Metric
from torchmetrics_tpu import observability as obs
from torchmetrics_tpu.parallel import coalesce as C
from torchmetrics_tpu.parallel import shard_map as shard_map_compat
from torchmetrics_tpu.parallel import sync as S
from torchmetrics_tpu.reliability import FlakyGather, ReliabilityConfig, RetryPolicy
from torchmetrics_tpu.utilities.exceptions import TransientRuntimeError

# --------------------------------------------------------------- world fakes


class CoalescedWorld:
    """dist_sync_fn simulating N ranks for the coalesced plane: call 0 answers
    the metadata collective, call k answers bucket k-1, each row produced by
    the same payload builders the real rank would run."""

    def __init__(self, states_per_rank, reductions):
        self.states_per_rank = states_per_rank
        self.reductions = reductions
        self.calls = 0
        self.metas = None

    def __call__(self, value, group=None):
        k = self.calls
        self.calls += 1
        if k == 0:
            self.metas = [
                C.build_local_metadata([s], [self.reductions]) for s in self.states_per_rank
            ]
            return [jnp.asarray(m) for m in self.metas]
        return [
            C.build_bucket_payload([s], [self.reductions], k - 1, self.metas)
            for s in self.states_per_rank
        ]


def per_leaf_world(states_per_rank):
    """dist_sync_fn replaying the per-leaf plane: one call per leaf in dict
    order, each returning every rank's prepared (list states pre-concatenated)
    value."""
    order = list(states_per_rank[0])
    counter = {"i": 0}

    def prepared(v):
        if isinstance(v, list):
            if not v:
                return jnp.zeros((0,), jnp.float32)
            return jnp.concatenate([jnp.atleast_1d(jnp.asarray(x)) for x in v], axis=0)
        return jnp.asarray(v)

    def fake(value, group=None):
        name = order[counter["i"] % len(order)]
        counter["i"] += 1
        return [prepared(s[name]) for s in states_per_rank]

    return fake


def _assert_state_equal(a, b, context=""):
    assert set(a) == set(b)
    for k in a:
        va, vb = a[k], b[k]
        if isinstance(va, list) or isinstance(vb, list):
            assert isinstance(va, list) and isinstance(vb, list), f"{context}:{k}"
            assert len(va) == len(vb), f"{context}:{k}"
            for x, y in zip(va, vb):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y), err_msg=f"{context}:{k}")
        else:
            assert jnp.asarray(va).dtype == jnp.asarray(vb).dtype, f"{context}:{k}"
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb), err_msg=f"{context}:{k}")


# ------------------------------------------------------- cross-process parity


def _make_rank_state(rng, rank, world, empty_cat=False):
    """One rank's state covering every reduction tag and mixed dtypes."""
    k = int(rng.integers(1, 5))  # uneven cat length per rank
    cat_list = (
        []
        if empty_cat
        else [jnp.asarray(rng.normal(size=(int(rng.integers(1, 3)), 2)).astype(np.float32)) for _ in range(k)]
    )
    return {
        "s_f32": jnp.asarray(rng.normal(size=(3, 2)).astype(np.float32)),
        "s_bf16": jnp.asarray(rng.normal(size=(4,)).astype(np.float32)).astype(jnp.bfloat16),
        "s_i32": jnp.asarray(rng.integers(0, 100, (2, 2)).astype(np.int32)),
        "mean_f32": jnp.asarray(rng.normal(size=(3,)).astype(np.float32)),
        "mx": jnp.asarray(np.float32(rng.normal())),
        "mn_bf16": jnp.asarray(rng.normal(size=(2,)).astype(np.float32)).astype(jnp.bfloat16),
        "cat_t": jnp.asarray(rng.normal(size=(k, 3)).astype(np.float32)),
        "cat_l": cat_list,
        "custom": jnp.asarray(rng.normal(size=(2,)).astype(np.float32)),
        "none_t": jnp.asarray(rng.normal(size=(2,)).astype(np.float32)),
    }


_FULL_REDUCTIONS = {
    "s_f32": "sum",
    "s_bf16": "sum",
    "s_i32": "sum",
    "mean_f32": "mean",
    "mx": "max",
    "mn_bf16": "min",
    "cat_t": "cat",
    "cat_l": "cat",
    "custom": lambda stacked: jnp.sum(stacked * 2.0, axis=0),
    "none_t": None,
}


@pytest.mark.parametrize("world", [2, 3, 4])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_coalesced_equals_per_leaf_all_tags(world, seed):
    """Bucketed sync == per-leaf sync, bitwise, for every tag, mixed dtypes
    (incl. bf16), uneven cat shapes, and a zero-update rank."""
    rng = np.random.default_rng(seed)
    states = [
        _make_rank_state(rng, r, world, empty_cat=(r == world - 1 and seed % 2 == 0))
        for r in range(world)
    ]
    coal = S.process_sync(dict(states[0]), _FULL_REDUCTIONS, dist_sync_fn=CoalescedWorld(states, _FULL_REDUCTIONS))
    leaf = S._process_sync_per_leaf(dict(states[0]), _FULL_REDUCTIONS, dist_sync_fn=per_leaf_world(states))
    _assert_state_equal(coal, leaf, context=f"world={world} seed={seed}")


def test_coalesced_collective_count():
    """3 dtypes in the state table → 1 metadata + 3 bucket collectives, vs one
    gather per leaf (10 leaves) on the per-leaf plane."""
    rng = np.random.default_rng(7)
    states = [_make_rank_state(rng, r, 2) for r in range(2)]
    fw = CoalescedWorld(states, _FULL_REDUCTIONS)
    S.process_sync(dict(states[0]), _FULL_REDUCTIONS, dist_sync_fn=fw)
    assert fw.calls == 4  # metadata + f32 + bf16 + i32
    plan = C.collective_counts([states[0]], [_FULL_REDUCTIONS])
    # per-leaf: 10 leaves × (shape exchange + payload gather)
    assert plan["process_coalesced"] == 4 and plan["process_per_leaf"] == 20


def test_weighted_mean_rides_sum_bucket():
    """MeanMetric-style weighted mean: value and weight are both "sum" states
    and must ride the same sum bucket, reproducing the per-leaf fold."""
    ms = [tm.aggregation.MeanMetric() for _ in range(3)]
    vals = ([1.0, 5.0], [2.0], [10.0, 20.0, 30.0])
    for m, v in zip(ms, vals):
        m.update(jnp.asarray(v))
    states = [dict(m._state) for m in ms]
    reds = ms[0]._reductions
    fw = CoalescedWorld(states, reds)
    out = S.process_sync(dict(states[0]), reds, dist_sync_fn=fw)
    leaf = S._process_sync_per_leaf(dict(states[0]), reds, dist_sync_fn=per_leaf_world(states))
    _assert_state_equal(out, leaf)
    assert fw.calls == 2  # one metadata + one f32 sum bucket for value AND weight
    expected = np.mean([x for chunk in vals for x in chunk])
    got = float(out["mean_value"]) / float(out["weight"]) if "weight" in out else None
    if got is not None:
        np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_mangled_metadata_falls_back_to_per_leaf():
    """An injected gather that rewrites payload values (the classic rank-offset
    fake) breaks the metadata decode — the plane must fall back to per-leaf and
    still produce the per-leaf answer."""
    fake = lambda v, g=None: [jnp.asarray(v) + i for i in range(3)]
    out = S.process_sync({"v": jnp.asarray(4.0)}, {"v": "mean"}, dist_sync_fn=fake)
    np.testing.assert_allclose(float(out["v"]), 5.0)


def test_injected_gather_rejecting_metadata_falls_back():
    """A user's dist_sync_fn written against the documented per-leaf seam may
    assert on its input — a deterministic rejection of the metadata vector
    must fall back to the per-leaf plane (transients still reach the retry
    layer, pinned by the FlakyGather tests)."""
    def strict_fake(v, g=None):
        assert jnp.asarray(v).dtype == jnp.float32, "my seam only ships f32 states"
        return [jnp.asarray(v), jnp.asarray(v)]

    out = S.process_sync({"x": jnp.asarray([1.0, 2.0])}, {"x": "sum"}, dist_sync_fn=strict_fake)
    np.testing.assert_allclose(np.asarray(out["x"]), [2.0, 4.0])


def test_fallback_sync_still_counts_its_collectives():
    """collectives_per_sync stays honest on fallback: the metadata collective
    that ran before the per-leaf fallback is counted alongside the per-leaf
    gathers."""
    fake = lambda v, g=None: [jnp.asarray(v) + i for i in range(2)]  # mangles metadata
    with obs.telemetry_session() as rec:
        S.process_sync({"a": jnp.asarray(1.0), "b": jnp.asarray(2.0)}, {"a": "sum", "b": "sum"}, dist_sync_fn=fake)
        snap = rec.counters.snapshot()
    assert snap["gather_calls"] == 2  # per-leaf plane ran
    assert snap["sync_collectives"] == 3  # 1 metadata (before fallback) + 2 leaves


def test_mixed_dtype_across_ranks_raises():
    states = [{"x": jnp.zeros((2,), jnp.float32)}, {"x": jnp.zeros((2,), jnp.int32)}]
    with pytest.raises(ValueError, match="same dtype"):
        S.process_sync(dict(states[0]), {"x": "sum"}, dist_sync_fn=CoalescedWorld(states, {"x": "sum"}))


def test_unsupported_dtype_raises_after_metadata_exchange():
    states = [{"x": jnp.zeros((2,), jnp.complex64)}]
    with pytest.raises(ValueError, match="unsupported dtype"):
        S.process_sync(dict(states[0]), {"x": "sum"}, dist_sync_fn=CoalescedWorld(states, {"x": "sum"}))


# ------------------------------------------------------- reliability contract


def test_flaky_gather_retries_under_coalescing():
    """FlakyGather raises on the first (metadata) collective; the retry re-runs
    the whole coalesced sync and the recovered value equals the global one."""
    states_per_rank = None

    class _Sum(Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("x", default=np.zeros(()), dist_reduce_fx="sum")

        def _batch_state(self, x):
            return {"x": jnp.asarray(x, jnp.float32).sum()}

        def _compute(self, state):
            return state["x"]

    inner = CoalescedWorld.__call__  # bound later
    world_states = [{"x": jnp.asarray(3.0)}, {"x": jnp.asarray(4.0)}]
    replay = CoalescedWorld(world_states, {"x": "sum"})
    flaky = FlakyGather(inner=replay, fail_times=1)
    m = _Sum(
        dist_sync_fn=flaky,
        distributed_available_fn=lambda: True,
        reliability=ReliabilityConfig(retry=RetryPolicy(max_attempts=3, backoff_base=0.001)),
    )
    m.update(np.asarray(3.0))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        val = m.compute()
    assert flaky.failures == 1
    np.testing.assert_allclose(float(val), 7.0)
    np.testing.assert_allclose(float(m._state["x"]), 3.0)  # local state restored


def test_flaky_gather_exhausted_rolls_back():
    """Retry budget exhausted mid-coalesced-sync: the metric must stay at its
    last good state (nothing committed)."""
    flaky = FlakyGather(inner=lambda v, g=None: [v, v], fail_times=10)
    m = tm.aggregation.SumMetric(
        dist_sync_fn=flaky,
        distributed_available_fn=lambda: True,
        reliability=ReliabilityConfig(retry=RetryPolicy(max_attempts=2, backoff_base=0.001)),
    )
    m.update(jnp.asarray([1.0, 2.0]))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        with pytest.raises(TransientRuntimeError):
            m.sync()
    assert not m._is_synced
    np.testing.assert_allclose(float(m._state["sum_value"]), 3.0)


def test_collection_flaky_gather_rolls_back_all_members():
    """A faulty bucketed gather under MetricCollection.sync leaves EVERY member
    at its last good state (atomic commit), then a retrying collection
    recovers."""
    flaky = FlakyGather(inner=lambda v, g=None: [jnp.asarray(v), jnp.asarray(v)], fail_times=1)
    pol = ReliabilityConfig(retry=RetryPolicy(max_attempts=3, backoff_base=0.001))
    coll = MetricCollection({
        "s": tm.aggregation.SumMetric(dist_sync_fn=flaky, reliability=pol),
        "m": tm.aggregation.MaxMetric(dist_sync_fn=flaky, reliability=pol),
    }, compute_groups=False)
    coll["s"].update(jnp.asarray([1.0, 2.0]))
    coll["m"].update(jnp.asarray([5.0]))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        coll.sync(distributed_available=lambda: True)
    assert flaky.failures == 1  # first collective failed, whole sync retried
    np.testing.assert_allclose(float(coll["s"]._state["sum_value"]), 6.0)  # v,v world
    coll.unsync()
    np.testing.assert_allclose(float(coll["s"]._state["sum_value"]), 3.0)
    np.testing.assert_allclose(float(coll["m"]._state["max_value"]), 5.0)


# ------------------------------------------------- collection-level coalescing


def _stat_collection(compute_groups):
    from torchmetrics_tpu.classification import (
        MulticlassAccuracy,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )

    metrics = {
        f"{cls.__name__}_{avg}": cls(5, average=avg, validate_args=False)
        for cls in (MulticlassAccuracy, MulticlassF1Score, MulticlassPrecision, MulticlassRecall)
        for avg in ("micro", "macro", "weighted", "none")
    }
    return MetricCollection(metrics, compute_groups=compute_groups)


def test_collection_sync_16_metrics_under_4_collectives():
    """The acceptance shape: 16 fixed-shape metrics sync in ≤ 4 collectives
    (vs ≥ 16 per-leaf), values identical to per-member syncs."""
    coll = _stat_collection(compute_groups=False)
    rng = np.random.default_rng(3)
    preds = jnp.asarray(rng.normal(size=(64, 5)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 5, 64, dtype=np.int32))
    coll.update(preds, target)
    ref = {k: np.asarray(v) for k, v in coll.compute().items()}
    with obs.telemetry_session() as rec:
        coll.sync(distributed_available=lambda: True)
        snap = rec.counters.snapshot()
    synced = {k: np.asarray(v) for k, v in coll.compute().items()}
    coll.unsync()
    assert snap["sync_calls"] == 1
    assert 0 < snap["sync_collectives"] <= 4
    assert snap["gathers_coalesced"] == 16 * 4  # every tp/fp/tn/fn leaf coalesced
    assert snap["gather_calls"] == 0  # nothing fell back to per-leaf
    assert snap.summary(brief=True)["collectives_per_sync"] <= 4.0
    for k in ref:  # world of one: synced values == local values
        np.testing.assert_allclose(synced[k], ref[k], err_msg=k)


def test_collection_sync_fused_groups_charged_once():
    """Fused compute-group members ALIAS one state dict: the coalesced sync
    gathers it once, members re-alias through sync AND unsync."""
    coll = _stat_collection(compute_groups=True)
    rng = np.random.default_rng(4)
    preds = jnp.asarray(rng.normal(size=(32, 5)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 5, 32, dtype=np.int32))
    coll.update(preds, target)
    assert len(coll.compute_groups) == 1  # the whole family shares tp/fp/tn/fn
    with obs.telemetry_session() as rec:
        coll.sync(distributed_available=lambda: True)
        snap = rec.counters.snapshot()
    assert snap["gathers_coalesced"] == 4  # ONE shared dict → 4 leaves, charged once
    members = list(coll.values())
    assert all(m._state is members[0]._state for m in members)  # aliasing kept
    coll.unsync()
    assert all(m._state is members[0]._state for m in members)  # ...and after unsync


def test_collection_mixed_seams_fall_back_to_per_member():
    """Members with different dist_sync_fn seams cannot share a collective —
    the collection must sync them per-member (same values as before)."""
    fake_a = lambda v, g=None: [jnp.asarray(v), jnp.asarray(v)]
    fake_b = lambda v, g=None: [jnp.asarray(v), jnp.asarray(v), jnp.asarray(v)]
    coll = MetricCollection({
        "a": tm.aggregation.SumMetric(dist_sync_fn=fake_a),
        "b": tm.aggregation.SumMetric(dist_sync_fn=fake_b),
    }, compute_groups=False)
    coll["a"].update(jnp.asarray([1.0]))
    coll["b"].update(jnp.asarray([1.0]))
    coll.sync(distributed_available=lambda: True)
    np.testing.assert_allclose(float(coll["a"]._state["sum_value"]), 2.0)
    np.testing.assert_allclose(float(coll["b"]._state["sum_value"]), 3.0)
    coll.unsync()


def test_compute_presyncs_collection_once():
    """MetricCollection.compute() pre-syncs every sync_on_compute member in ONE
    coalesced sync instead of one per member."""
    coll = _stat_collection(compute_groups=False)
    rng = np.random.default_rng(5)
    preds = jnp.asarray(rng.normal(size=(32, 5)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 5, 32, dtype=np.int32))
    coll.update(preds, target)
    for m in coll.values():
        m.distributed_available_fn = lambda: True
    with obs.telemetry_session() as rec:
        values = coll.compute()
        snap = rec.counters.snapshot()
    assert snap["sync_calls"] == 1  # one coalesced sync for all 16 members
    assert snap["sync_collectives"] <= 4
    assert not any(m._is_synced for m in coll.values())  # unsynced after compute
    assert len(values) >= 16


def test_compute_inside_sync_context_does_not_resync():
    """A pre-synced metric computes on the synced state instead of raising
    (the guard that enables collection-level pre-sync)."""
    m = tm.aggregation.SumMetric(
        dist_sync_fn=lambda v, g=None: [jnp.asarray(v), jnp.asarray(v)],
        distributed_available_fn=lambda: True,
    )
    m.update(jnp.asarray([2.0]))
    m.sync()
    val = m.compute()  # previously raised "already been synced"
    np.testing.assert_allclose(float(val), 4.0)
    m.unsync()
    np.testing.assert_allclose(float(m._state["sum_value"]), 2.0)


# ----------------------------------------------------------- in-graph plane


def _mesh8():
    return jax.make_mesh((8,), ("dp",), devices=jax.devices()[:8])


def test_ingraph_bucketed_reduce_matches_per_leaf():
    """All tags, mixed dtypes: bucketed reduce_states == per-leaf, bitwise,
    inside shard_map over the 8-device CPU mesh."""
    from jax.sharding import PartitionSpec as P

    state = {
        "a": jnp.arange(4.0),
        "b": jnp.asarray(2.0),
        "i": jnp.arange(6, dtype=jnp.int32),
        "m": jnp.asarray(3.0),
        "bf": jnp.asarray([1.5, -2.0], jnp.bfloat16),
        "cat": jnp.arange(2.0),
        "cust": jnp.asarray([1.0, 4.0]),
        "skip": jnp.asarray(9.0),
    }
    reds = {
        "a": "sum", "b": "max", "i": "sum", "m": "mean", "bf": "min",
        "cat": "cat", "cust": lambda g: jnp.max(g, axis=0), "skip": None,
    }
    mesh = _mesh8()
    f_new = jax.jit(shard_map_compat(lambda s: S.reduce_states(s, reds, "dp"), mesh=mesh,
                                     in_specs=(P(),), out_specs=P(), check_vma=False))
    f_old = jax.jit(shard_map_compat(lambda s: S.reduce_states_per_leaf(s, reds, "dp"), mesh=mesh,
                                     in_specs=(P(),), out_specs=P(), check_vma=False))
    a, b = f_new(state), f_old(state)
    for k in state:
        assert jnp.asarray(a[k]).dtype == jnp.asarray(b[k]).dtype, k
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)


def test_ingraph_collection_reduce_coalesced_matches_per_member():
    """PureCollection.reduce coalesces across members (including one that
    overrides reduce_state and must keep its exact fold)."""
    from jax.sharding import PartitionSpec as P

    from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassF1Score
    from torchmetrics_tpu.regression import PearsonCorrCoef

    coll = MetricCollection({
        "acc": MulticlassAccuracy(5, average="micro", validate_args=False),
        "f1": MulticlassF1Score(5, average="macro", validate_args=False),
        "pearson": PearsonCorrCoef(),
    })
    pure = coll.as_pure()
    mesh = _mesh8()
    rng = np.random.default_rng(6)
    preds = jnp.asarray(rng.normal(size=(32, 5)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 5, 32, dtype=np.int32))
    reg_p = jnp.asarray(rng.normal(size=(32,)).astype(np.float32))
    reg_t = reg_p * 0.5 + jnp.asarray(rng.normal(size=(32,)).astype(np.float32)) * 0.1

    def step(preds, target, rp, rt):
        states = pure.init()
        states["acc"] = coll["acc"].update_state(states["acc"], preds, target)
        states["f1"] = coll["f1"].update_state(states["f1"], preds, target)
        states["pearson"] = coll["pearson"].update_state(states["pearson"], rp, rt)
        return pure.reduce(states, "dp")

    def step_per_member(preds, target, rp, rt):
        states = pure.init()
        states["acc"] = coll["acc"].update_state(states["acc"], preds, target)
        states["f1"] = coll["f1"].update_state(states["f1"], preds, target)
        states["pearson"] = coll["pearson"].update_state(states["pearson"], rp, rt)
        return {n: coll[n].reduce_state(states[n], "dp") for n in states}

    P4 = (P("dp"), P("dp"), P("dp"), P("dp"))
    f_new = jax.jit(shard_map_compat(step, mesh=mesh, in_specs=P4, out_specs=P(), check_vma=False))
    f_old = jax.jit(shard_map_compat(step_per_member, mesh=mesh, in_specs=P4, out_specs=P(), check_vma=False))
    a, b = f_new(preds, target, reg_p, reg_t), f_old(preds, target, reg_p, reg_t)
    for name in a:
        for k in a[name]:
            np.testing.assert_allclose(
                np.asarray(a[name][k]), np.asarray(b[name][k]), rtol=1e-6, err_msg=f"{name}.{k}"
            )


# ------------------------------------------------ fleet counter rollup plane


def test_gather_metadata_vector_is_single_collective():
    calls = {"n": 0}

    def fake(v, g=None):
        calls["n"] += 1
        return [jnp.asarray(v), jnp.asarray(v)]

    rows = S.gather_metadata_vector([3, (1 << 40) + 7], dist_sync_fn=fake)
    assert calls["n"] == 1  # ONE collective — no per-leaf shape round-trip
    assert rows == [[3, (1 << 40) + 7]] * 2


def test_fleet_rollup_piggybacks_on_coalesced_sync(monkeypatch):
    """After a coalesced sync under an active session, summary(fleet=True)
    reuses the counter rows the sync's metadata collective shipped — zero
    extra collectives."""
    C.clear_fleet_mailbox()
    m = tm.aggregation.SumMetric()
    m.update(jnp.asarray([1.0, 2.0]))
    with obs.telemetry_session() as rec:
        m.sync(distributed_available=lambda: True)  # real world-of-one collectives
        m.unsync()
        rows = C.fleet_counter_rows()
        assert rows is not None
        assert rows[1] == 0 and len(rows[0]) == 1  # one rank, local index 0

        def boom(*a, **k):
            raise AssertionError("fleet rollup launched a collective after a coalesced sync")

        monkeypatch.setattr(S, "gather_metadata_vector", boom)
        fleet = obs.gather_counters()
        assert fleet.ranks == 1
        assert fleet.totals["sync_calls"] == rec.counters.value("sync_calls")
    C.clear_fleet_mailbox()


def test_fleet_mailbox_invalidated_by_new_session():
    C.clear_fleet_mailbox()
    m = tm.aggregation.SumMetric()
    m.update(jnp.asarray([1.0]))
    with obs.telemetry_session():
        m.sync(distributed_available=lambda: True)
        m.unsync()
        assert C.fleet_counter_rows() is not None
    with obs.telemetry_session():
        assert C.fleet_counter_rows() is None  # stale rows never leak across sessions
    C.clear_fleet_mailbox()
