"""Cost & memory accounting + fleet aggregation — the PR-4 acceptance contract:

- every dispatch key the counters record as a compile has a cost entry
  (``cost_snapshot()`` keys == compile-counter keys), harvested with zero
  device→host traffic (transfer-guard enforced);
- ``state_memory()`` totals match the sum of state-leaf ``nbytes`` with zero
  D2H under the transfer guard, fused-group aliases are not double-counted,
  and the unbounded-growth sentinel fires once per list state;
- ``aggregate_counters()`` over N simulated ranks equals the sum of the N
  per-rank snapshots, and the distributed rollup rides the parallel/sync
  gather plane with a metadata-sized payload."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchmetrics_tpu as tm
from torchmetrics_tpu import MetricCollection, observability as obs
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.observability import memory as obs_memory
from torchmetrics_tpu.parallel import sync as par_sync

pytestmark = pytest.mark.telemetry


def _x(n=8, seed=0):
    return jnp.asarray(np.random.default_rng(seed).random(n).astype(np.float32))


class _SumState(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("s", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, x):
        return {"s": x.sum()}

    def _compute(self, state):
        return state["s"]


# ------------------------------------------------------------------ costs


def test_cost_entries_reconcile_with_compile_keys():
    """Acceptance: cost_snapshot() keys == compile-counter keys, with the run
    totals weighted by how often each compiled signature actually dispatched."""
    m = _SumState()
    with obs.telemetry_session() as rec:
        with jax.transfer_guard_device_to_host("disallow"):  # harvest is aval-only
            for _ in range(3):
                m.update(_x(8))
            m.update(_x(4))  # second signature -> second compile + cost entry
    snap = rec.counters.snapshot()
    costs = rec.cost_snapshot()
    assert set(costs) == set(snap.per_key)
    key = next(iter(costs))
    sigs = costs[key]
    assert len(sigs) == snap.per_key[key]["compiles"] == 2
    for rec_d in sigs.values():
        assert rec_d["available"] is True
        assert rec_d["flops"] > 0 and rec_d["bytes_accessed"] > 0
        assert rec_d["argument_bytes"] > 0
    # dispatch-weighted totals: sum over signatures of per-call flops x count
    sig_counts = snap.per_key[key]["sig_counts"]
    assert sum(sig_counts.values()) == snap["dispatches"] == 4
    expected = sum(sigs[s]["flops"] * n for s, n in sig_counts.items())
    totals = snap.cost_totals()
    assert totals["run_flops"] == pytest.approx(expected)
    assert totals["compiled_programs"] == 2 and totals["unavailable"] == 0
    # the non-brief counters summary folds the same numbers in
    full = snap.summary()
    assert full["cost_totals"]["run_flops"] == pytest.approx(expected)
    assert set(full["costs"]) == set(snap.per_key)


def test_cost_placeholder_keeps_reconciliation_for_eager_path():
    """jit=False metrics still count compiles by signature novelty; the cost
    registry records an unavailable placeholder so the 1:1 key invariant holds."""
    m = _SumState(jit=False)
    with obs.telemetry_session() as rec:
        m.update(_x())
    snap = rec.counters.snapshot()
    costs = rec.cost_snapshot()
    assert set(costs) == set(snap.per_key) and len(costs) == 1
    (record,) = [r for sigs in costs.values() for r in sigs.values()]
    assert record["available"] is False and "lowerable" in record["error"]
    assert snap.cost_totals()["unavailable"] == 1


def test_cost_accounting_config_off():
    m = _SumState()
    with obs.telemetry_session(obs.TelemetryConfig(cost_accounting=False)) as rec:
        m.update(_x())
    assert rec.cost_snapshot() == {}
    assert "costs" not in rec.counters.snapshot().summary()


def test_cost_snapshot_diff_isolates_new_programs():
    m = _SumState()
    with obs.telemetry_session() as rec:
        m.update(_x(8))
        first = rec.counters.snapshot()
        m.update(_x(8))  # cache hit: no new program
        m.update(_x(4))  # fresh compile
        delta = rec.counters.snapshot().diff(first)
    (sigs,) = delta.costs.values()
    assert len(sigs) == 1  # only the (4,) program is new in the window
    (key_rec,) = delta.per_key.values()
    assert key_rec["sig_counts"] == {"float32(8,)": 1, "float32(4,)": 1}


def test_module_level_cost_snapshot():
    assert obs.cost_snapshot() == {}  # disabled -> empty, never raises
    m = _SumState()
    with obs.telemetry_session():
        m.update(_x())
        assert set(obs.cost_snapshot()) == {f"_SumState#0.update"}


# ----------------------------------------------------------------- memory


def test_state_memory_matches_leaf_nbytes_zero_d2h():
    """Acceptance: totals == sum of state-leaf nbytes, under a disallow guard."""
    m = tm.CatMetric()
    m.update(_x(8))
    m.update(_x(8))
    s = tm.SumMetric()
    s.update(_x(8))
    with jax.transfer_guard_device_to_host("disallow"):
        cat_mem = m.state_memory()
        sum_mem = s.state_memory()
    expected = sum(
        leaf.size * leaf.dtype.itemsize
        for v in m._state.values()
        for leaf in (v if isinstance(v, list) else [v])
    )
    assert cat_mem["total_bytes"] == expected == 64
    assert cat_mem["states"]["value"] == {"kind": "list", "nbytes": 64, "elements": 2}
    assert sum_mem["states"]["sum_value"]["kind"] == "tensor"
    assert sum_mem["states"]["sum_value"]["dtype"] == "float32"
    assert sum_mem["total_bytes"] == 4


def test_collection_state_memory_dedups_aliased_groups():
    col = MetricCollection({"s1": tm.SumMetric(), "s2": tm.SumMetric()})
    col.update(_x())
    col.update(_x())  # groups derived: s2 aliases s1's state dict
    report = col.state_memory()
    aliased = [n for n, r in report["members"].items() if "aliased_to" in r]
    holders = [n for n, r in report["members"].items() if "aliased_to" not in r]
    assert len(aliased) == 1 and len(holders) == 1
    assert report["members"][aliased[0]]["aliased_to"] == holders[0]
    # the shared dict is charged once: total == one metric's footprint
    assert report["total_bytes"] == report["members"][holders[0]]["total_bytes"] == 4


def test_peak_tracking_and_growth_sentinel_warns_once():
    cfg = obs.TelemetryConfig(state_growth_warn_bytes=40)
    m = tm.CatMetric()
    with obs.telemetry_session(cfg) as rec:
        m.update(_x(8))  # 32 bytes: under threshold
        with pytest.warns(UserWarning, match="State growth sentinel.*CatMetric#0.value"):
            m.update(_x(8))  # 64 bytes: crosses
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # crossed already -> warned once only
            m.update(_x(8))
    events = rec.events_of("state_growth")
    assert len(events) == 1
    assert events[0].payload["nbytes"] == 64 and events[0].payload["elements"] == 2
    mem = rec.memory_snapshot()["CatMetric#0"]
    assert mem["current_bytes"] == mem["peak_bytes"] == 96
    assert mem["per_state_peak"]["value"] == 96


def test_memory_tracking_config_off():
    m = tm.CatMetric()
    with obs.telemetry_session(obs.TelemetryConfig(track_state_memory=False)) as rec:
        m.update(_x())
    assert rec.memory_snapshot() == {}


def test_telemetry_summary_carries_state_bytes():
    col = MetricCollection({"s1": tm.SumMetric(), "s2": tm.SumMetric()})
    with obs.telemetry_session():
        col.update(_x())
        summary = col.telemetry_summary()
    assert summary["state_memory_bytes"] == 4  # aliased pair counted once
    assert all(info["state_bytes"] == 4 for info in summary["members"].values())


def test_state_memory_helpers_are_metadata_only():
    assert obs_memory.leaf_nbytes(np.zeros((4, 2), np.float64)) == 64
    assert obs_memory.leaf_nbytes("not an array") == 0
    report = obs_memory.state_memory({"a": [np.zeros(3, np.float32)], "b": np.zeros((), np.int64)})
    assert report["total_bytes"] == 12 + 8


# ------------------------------------------------------------------ fleet


def _snapshot_with(dispatches=0, sync_time_us=0, sync_calls=0, key=None):
    c = obs.Counters()
    for i in range(dispatches):
        c.record_dispatch(key or "M#0.update", "f32(4,)")
    for _ in range(sync_calls):
        c.record_sync(16)
    c.record_sync_time(sync_time_us / 1e6)
    return c.snapshot()


def test_aggregate_counters_equals_sum_of_ranks():
    """Acceptance: fleet totals == exact fieldwise sum of per-rank snapshots."""
    ranks = [
        _snapshot_with(dispatches=3, sync_time_us=100, sync_calls=1, key="A#0.update"),
        _snapshot_with(dispatches=5, sync_time_us=900, sync_calls=1, key="A#0.update"),
        _snapshot_with(dispatches=2, sync_time_us=400, sync_calls=2, key="B#0.update"),
    ]
    fleet = obs.aggregate_counters(ranks)
    assert fleet.ranks == 3
    for field in obs.COUNTER_FIELDS:
        assert fleet.totals[field] == sum(r.counts[field] for r in ranks), field
    assert fleet["dispatches"] == 10 and fleet["sync_calls"] == 4
    # per-key union: shared keys sum, distinct keys survive
    assert fleet.per_key["A#0.update"]["compiles"] == 2  # one first-sight per rank
    assert fleet.per_key["A#0.update"]["sig_counts"] == {"f32(4,)": 8}
    assert fleet.per_key["B#0.update"]["compiles"] == 1
    # straggler attribution: rank 1 holds the sync-time max
    skew = fleet.stragglers["sync_time_us"]
    assert (skew["min"], skew["max"], skew["skew"]) == (100, 900, 800)
    assert skew["min_rank"] == 0 and skew["max_rank"] == 1
    brief = fleet.summary(brief=True)
    assert brief["fleet"] is True and brief["ranks"] == 3 and brief["dispatches"] == 10
    full = fleet.summary()
    assert len(full["per_rank"]) == 3 and full["totals"]["dispatches"] == 10


def test_aggregate_counters_accepts_vectors_and_rejects_bad_shapes():
    snap = _snapshot_with(dispatches=4)
    fleet = obs.aggregate_counters([snap, snap.counts_vector(), dict(snap.counts)])
    assert fleet.totals["dispatches"] == 12
    with pytest.raises(ValueError, match="at least one"):
        obs.aggregate_counters([])
    with pytest.raises(ValueError, match="entries"):
        obs.aggregate_counters([[1, 2, 3]])


def test_gather_counters_through_gather_plane():
    """The distributed rollup rides parallel/sync with a metadata payload: an
    injected 2-way gather doubles every total and keeps local per-key records."""
    m = _SumState()
    with obs.telemetry_session() as rec:
        for _ in range(4):
            m.update(_x())
        fleet = obs.gather_counters(dist_sync_fn=lambda v, g: [v, v])
        local = rec.counters.snapshot()
    assert fleet.ranks == 2
    for field in obs.COUNTER_FIELDS:
        assert fleet.totals[field] == 2 * local.counts[field], field
    assert fleet.per_key["_SumState#0.update"]["compiles"] == 1  # local records only
    # single process, no injected gather: a one-rank fleet, not an error
    solo = obs.gather_counters(local)
    assert solo.ranks == 1 and solo.totals == {f: local.counts[f] for f in obs.COUNTER_FIELDS}


def test_recorder_summary_fleet_mode():
    m = _SumState(
        distributed_available_fn=lambda: True,
        dist_sync_fn=lambda v, g: [v, v],
    )
    with obs.telemetry_session() as rec:
        m.update(_x())
        m.compute()  # fake-distributed: one sync with timed duration
        out = rec.summary(brief=True, fleet=True, dist_sync_fn=lambda v, g: [v, v])
    assert out["fleet"] is True and out["ranks"] == 2
    assert out["sync_calls"] == 2 * out["local"]["sync_calls"] == 2
    assert out["stragglers"]["sync_time_us"]["max"] >= 0
    # local-only summary stays the plain counters shape
    local = rec.summary(brief=True)
    assert "fleet" not in local and local["dispatches"] == 1


def test_gather_metadata_vector_single_process():
    assert par_sync.gather_metadata_vector([1, 2, 3]) == [[1, 2, 3]]
    doubled = par_sync.gather_metadata_vector([4, 5], dist_sync_fn=lambda v, g: [v, v])
    assert doubled == [[4, 5], [4, 5]]


def test_gather_metadata_vector_survives_int32_overflow():
    """Counters past 2**31 (a >2 GiB cumulative sync payload) must gather
    exactly despite jax's default x64-disabled int64→int32 downcast — the
    (hi, lo) split keeps values below 2**62 exact."""
    big = [2**31 + 5, 7 * 2**32, 0, 3]
    gathered = par_sync.gather_metadata_vector(big, dist_sync_fn=lambda v, g: [v, v])
    assert gathered == [big, big]
    with pytest.raises(ValueError, match="2\\*\\*62"):
        par_sync.gather_metadata_vector([-1])


def test_gather_counters_requires_session_or_snapshot():
    assert not obs.enabled()
    with pytest.raises(RuntimeError, match="active telemetry session"):
        obs.gather_counters()
