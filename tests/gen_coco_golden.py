"""Generate ``tests/_data/coco_golden.json`` from the COCOeval-semantics oracle.

Run as ``python tests/gen_coco_golden.py`` from the repo root. Fixtures are
deliberately UNrestricted — unlike the round-2 parity fixtures they exercise
crowd ground truths, all four area buckets (including explicit ``area`` fields
that differ from the box area), score ties, duplicate-box IoU ties, dense
overlaps (greedy-matcher exhaustion), custom ``max_detection_thresholds`` and
segmentation masks. Golden numbers come from ``tests/_coco_oracle.py``.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
from _coco_oracle import CocoOracle  # noqa: E402


def _boxes(rng, n, lo=0, hi=400, wmin=4, wmax=200):
    xy = rng.uniform(lo, hi, (n, 2))
    wh = rng.uniform(wmin, wmax, (n, 2))
    return np.concatenate([xy, xy + wh], -1).round(2)


def dense_overlap(rng):
    """Clustered boxes with duplicate boxes and tied scores: exhausts the greedy
    matcher (the regression case for the batched-scatter miscompile)."""
    preds, target = [], []
    for _ in range(20):
        centers = _boxes(rng, 4, 0, 200, 30, 120)
        gt, dt, scores, glab, dlab = [], [], [], [], []
        for c in centers:
            k = int(rng.integers(2, 5))
            for j in range(k):
                jitter = rng.uniform(-8, 8, 4).round(2)
                gt.append(c + jitter * (j > 0))
                glab.append(int(rng.integers(0, 3)))
            for j in range(int(rng.integers(2, 6))):
                jitter = rng.uniform(-10, 10, 4).round(2)
                dt.append(c + jitter)
                # tied scores on purpose
                scores.append(round(float(rng.choice([0.3, 0.5, 0.5, 0.9])), 2))
                dlab.append(int(rng.integers(0, 3)))
        # exact duplicate detection (IoU tie on the same gt)
        dt.append(dt[0])
        scores.append(scores[0])
        dlab.append(dlab[0])
        preds.append({"boxes": np.asarray(dt), "scores": np.asarray(scores), "labels": np.asarray(dlab)})
        target.append({"boxes": np.asarray(gt), "labels": np.asarray(glab)})
    return preds, target, {}


def crowds_and_areas(rng):
    """Crowd gts + all four area buckets + explicit area fields != box area."""
    preds, target = [], []
    for _ in range(30):
        ng = int(rng.integers(3, 12))
        sizes = rng.choice(["s", "m", "l"], ng)
        gt = []
        for s in sizes:
            lo, hi = {"s": (4, 28), "m": (40, 90), "l": (100, 280)}[s]
            gt.append(_boxes(rng, 1, 0, 400, lo, hi)[0])
        gt = np.asarray(gt)
        crowd = (rng.random(ng) < 0.25).astype(int)
        # explicit area overrides box area for a third of the gts
        area = np.where(
            rng.random(ng) < 0.33,
            rng.uniform(10, 10000, ng).round(1),
            np.zeros(ng),
        )
        glab = rng.integers(0, 5, ng)
        nd = int(rng.integers(2, 15))
        use_gt = rng.random(nd) < 0.6
        dt = np.where(
            use_gt[:, None],
            gt[rng.integers(0, ng, nd)] + rng.uniform(-6, 6, (nd, 4)).round(2),
            _boxes(rng, nd),
        )
        preds.append({
            "boxes": dt,
            "scores": rng.random(nd).round(3),
            "labels": rng.integers(0, 5, nd),
        })
        target.append({"boxes": gt, "labels": glab, "iscrowd": crowd, "area": area})
    return preds, target, {}


def custom_maxdets(rng):
    """Many detections per image with maxDets [1, 5, 10]."""
    preds, target = [], []
    for _ in range(15):
        ng = int(rng.integers(4, 10))
        gt = _boxes(rng, ng, 0, 300, 20, 150)
        nd = int(rng.integers(15, 30))
        dt = gt[rng.integers(0, ng, nd)] + rng.uniform(-12, 12, (nd, 4)).round(2)
        preds.append({
            "boxes": dt,
            "scores": rng.random(nd).round(3),
            "labels": rng.integers(0, 2, nd),
        })
        target.append({"boxes": gt, "labels": rng.integers(0, 2, ng)})
    return preds, target, {"max_detection_thresholds": [1, 5, 10]}


def edge_cases(rng):
    """Handcrafted: det matching only an ignored gt, empty preds/gts, crowd-only
    images, det outside every area bucket it could score in."""
    big = 150.0
    preds = [
        # det overlaps only a crowd gt -> matched-to-ignored, not a FP
        {"boxes": np.array([[10, 10, 50, 50]]), "scores": np.array([0.9]), "labels": np.array([0])},
        # empty prediction, non-empty gt
        {"boxes": np.zeros((0, 4)), "scores": np.zeros(0), "labels": np.zeros(0, int)},
        # non-empty prediction, empty gt
        {"boxes": np.array([[0, 0, 20, 20], [5, 5, 25, 25]]), "scores": np.array([0.7, 0.7]),
         "labels": np.array([0, 0])},
        # two dets, one gt: higher score takes it, tie broken by order
        {"boxes": np.array([[0, 0, big, big], [1, 1, big + 1, big + 1]]),
         "scores": np.array([0.5, 0.5]), "labels": np.array([1, 1])},
    ]
    target = [
        {"boxes": np.array([[12, 12, 48, 48]]), "labels": np.array([0]), "iscrowd": np.array([1])},
        {"boxes": np.array([[30, 30, 60, 60]]), "labels": np.array([0])},
        {"boxes": np.zeros((0, 4)), "labels": np.zeros(0, int)},
        {"boxes": np.array([[0, 0, big, big]]), "labels": np.array([1])},
    ]
    return preds, target, {}


def segm(rng):
    """Random blob masks with crowds; IoU ties via duplicated masks."""
    H = W = 32
    preds, target = [], []
    for _ in range(8):
        ng = int(rng.integers(2, 5))
        gmask = np.zeros((ng, H, W), bool)
        for j in range(ng):
            cx, cy = rng.integers(4, W - 4, 2)
            r = int(rng.integers(3, 10))
            yy, xx = np.mgrid[:H, :W]
            gmask[j] = (xx - cx) ** 2 + (yy - cy) ** 2 <= r * r
        nd = int(rng.integers(2, 6))
        dmask = np.zeros((nd, H, W), bool)
        for j in range(nd):
            base = gmask[rng.integers(0, ng)]
            noise = rng.random((H, W)) < 0.08
            dmask[j] = base ^ noise
        dmask[0] = gmask[0]  # exact-duplicate mask
        preds.append({
            "masks": dmask,
            "scores": rng.random(nd).round(3),
            "labels": rng.integers(0, 2, nd),
        })
        target.append({
            "masks": gmask,
            "labels": rng.integers(0, 2, ng),
            "iscrowd": (rng.random(ng) < 0.2).astype(int),
        })
    return preds, target, {"iou_type": "segm"}


FIXTURES = {
    "dense_overlap": dense_overlap,
    "crowds_and_areas": crowds_and_areas,
    "custom_maxdets": custom_maxdets,
    "edge_cases": edge_cases,
    "segm": segm,
}


def _ser_sample(d):
    out = {}
    for k, v in d.items():
        arr = np.asarray(v)
        if k == "masks":
            out[k] = np.packbits(arr.astype(np.uint8), axis=None).tolist() + [
                -1, *arr.shape
            ]  # packed bits + shape sentinel
        elif arr.dtype.kind == "f":
            out[k] = np.round(arr, 6).tolist()
        else:
            out[k] = arr.tolist()
    return out


def main() -> None:
    rng = np.random.default_rng(20260730)
    blob = {}
    for name, gen in FIXTURES.items():
        preds, target, opts = gen(rng)
        iou_type = opts.pop("iou_type", "bbox")
        oracle = CocoOracle(**opts)
        stats = oracle.stats(preds, target, iou_type=iou_type, class_metrics=True)
        blob[name] = {
            "opts": opts,
            "iou_type": iou_type,
            "preds": [_ser_sample(p) for p in preds],
            "target": [_ser_sample(t) for t in target],
            "stats": {
                k: (v if isinstance(v, list) else round(v, 12)) for k, v in stats.items()
            },
        }
        print(name, "map=%.6f map_small=%.4f map_medium=%.4f map_large=%.4f" % (
            stats["map"], stats["map_small"], stats["map_medium"], stats["map_large"]))
    path = os.path.join(os.path.dirname(__file__), "_data", "coco_golden.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(blob, f)
    print("wrote", path, f"({os.path.getsize(path)//1024} KiB)")


if __name__ == "__main__":
    main()
