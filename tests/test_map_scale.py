"""mAP correctness at COCO-val-like scale (VERDICT r3 #4).

The round-2 matcher miscompile only appeared at batch >= 64 — scale-dependent
wrongness is this evaluator's signature failure mode — so the oracle fuzz runs
once at >= 1k images / 80 classes / mixed crowds+areas with label-correlated
detections (real TPs across the score range, map ~0.11, not the ~7e-4 of
independent random labels). Compute-time budget is asserted alongside (BENCH_r03 was 2.52 s
at 500 imgs; target < 10 s at 1.2k).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from tests._coco_oracle import CocoOracle
from torchmetrics_tpu.detection import MeanAveragePrecision


def _box_masks(boxes: np.ndarray, canvas: int, scale: float) -> np.ndarray:
    """Filled-box masks on a small canvas (box coords / scale), COCO (N, H, W) bool."""
    n = boxes.shape[0]
    out = np.zeros((n, canvas, canvas), bool)
    yy, xx = np.mgrid[0:canvas, 0:canvas]
    for i in range(n):
        x0, y0, x1, y1 = boxes[i] / scale
        out[i] = (xx >= x0) & (xx < x1) & (yy >= y0) & (yy < y1)
    return out


def _coco_scale_dataset(rng, n_imgs: int, n_cls: int, masks: bool = False, canvas: int = 44):
    """Label-correlated detections: each det copies a gt box + label with jitter
    (80%) or is a random false positive, so precision curves populate at every
    threshold; crowds, explicit areas and score ties included."""
    preds, target = [], []
    for _ in range(n_imgs):
        ng = int(rng.integers(1, 12))
        nd = int(rng.integers(0, 16))
        gt = np.concatenate([rng.uniform(0, 400, (ng, 2)), np.zeros((ng, 2))], -1).astype(np.float32)
        gt[:, 2:] = gt[:, :2] + rng.uniform(4, 250, (ng, 2))
        gt_labels = rng.integers(0, n_cls, ng).astype(np.int32)
        boxes, labels = [], []
        for _ in range(nd):
            if ng and rng.random() < 0.8:
                j = int(rng.integers(0, ng))
                boxes.append(gt[j] + rng.uniform(-15, 15, 4).astype(np.float32))
                labels.append(gt_labels[j] if rng.random() < 0.9 else int(rng.integers(0, n_cls)))
            else:
                b = np.concatenate([rng.uniform(0, 400, 2), np.zeros(2)]).astype(np.float32)
                b[2:] = b[:2] + rng.uniform(4, 250, 2)
                boxes.append(b)
                labels.append(int(rng.integers(0, n_cls)))
        dt = np.stack(boxes).round(2) if nd else np.zeros((0, 4), np.float32)
        pred = {
            "boxes": dt,
            "scores": rng.choice([0.2, 0.5, 0.5, 0.8, 0.9], nd).astype(np.float32),
            "labels": np.asarray(labels, np.int32),
        }
        tgt = {
            "boxes": gt.round(2),
            "labels": gt_labels,
            "iscrowd": (rng.random(ng) < 0.15).astype(np.int32),
            "area": np.where(rng.random(ng) < 0.3, rng.uniform(10, 20000, ng), 0).astype(np.float32),
        }
        if masks:
            # boxes live in [0, ~650); /14 maps onto a 44-px canvas so the
            # largest boxes (>616 in box coords) clip at the right/bottom
            # border — clipped masks have mask-area < box-area, exercising the
            # segm area-bucket ignores
            pred["masks"] = _box_masks(dt, canvas, 14.0)
            tgt["masks"] = _box_masks(tgt["boxes"], canvas, 14.0)
        preds.append(pred)
        target.append(tgt)
    return preds, target


@pytest.mark.slow
def test_map_oracle_agreement_at_coco_val_scale():
    rng = np.random.default_rng(42)
    preds, target = _coco_scale_dataset(rng, 1200, 80)
    # scaling guard run first at quarter size: a quadratic regression shows up as
    # a blown-up large/small RATIO, immune to absolute host-speed noise
    small = MeanAveragePrecision(class_metrics=True)
    small.update(preds[:300], target[:300])
    t0 = time.time()
    small.compute()
    small_sec = max(time.time() - t0, 1e-3)

    metric = MeanAveragePrecision(class_metrics=True)
    metric.update(preds, target)
    t0 = time.time()
    res = {k: np.asarray(v) for k, v in metric.compute().items()}
    compute_sec = time.time() - t0

    # ~0.11 with this generator (+-15px jitter is harsh on small boxes) vs ~7e-4
    # for independent random labels: real matches populate every threshold
    assert float(res["map"]) > 0.05, "dataset must produce real matches for the test to mean anything"
    golden = CocoOracle().stats(preds, target, class_metrics=True)
    for key, val in golden.items():
        if key == "classes":
            assert res["classes"].tolist() == val
            continue
        np.testing.assert_allclose(
            np.asarray(res[key], np.float64), np.asarray(val), atol=1e-6, err_msg=key
        )
    # scale perf guard: linear scaling gives ratio ~4 for 4x the images (measured
    # ~5.6 s at 1.2k vs ~1.5 s at 300); quadratic behavior would push it to ~16.
    # Ratio-based so host contention can't flake it; loose absolute backstop too.
    ratio = compute_sec / small_sec
    assert ratio < 10.0, f"mAP compute scaling ratio 300->1200 imgs is {ratio:.1f} (quadratic regression?)"
    assert compute_sec < 60.0, f"mAP compute at 1.2k imgs took {compute_sec:.1f}s"


@pytest.mark.slow
def test_map_oracle_agreement_at_full_coco_val2017_scale():
    """The advertised scale (BASELINE config #3): 5,000 images / 80 classes —
    COCO-val-2017-sized — with crowds, explicit areas, score ties AND segm masks,
    evaluated as iou_type=("bbox", "segm") in one metric. Cell-for-cell oracle
    agreement plus a tightened near-linear scaling assertion (VERDICT r4 #3:
    the old <10x-for-4x bound only excluded quadratic blowup)."""
    rng = np.random.default_rng(20260731)
    preds, target = _coco_scale_dataset(rng, 5000, 80, masks=True)

    quarter = MeanAveragePrecision(iou_type=("bbox", "segm"), class_metrics=True)
    quarter.update(preds[:1250], target[:1250])
    t0 = time.time()
    quarter.compute()
    quarter_sec = max(time.time() - t0, 1e-3)

    metric = MeanAveragePrecision(iou_type=("bbox", "segm"), class_metrics=True)
    metric.update(preds, target)
    t0 = time.time()
    res = {k: np.asarray(v) for k, v in metric.compute().items()}
    compute_sec = time.time() - t0

    assert float(res["bbox_map"]) > 0.05, "dataset must produce real matches"
    oracle = CocoOracle()
    for iou_type in ("bbox", "segm"):
        golden = oracle.stats(preds, target, iou_type=iou_type, class_metrics=True)
        for key, val in golden.items():
            if key == "classes":
                assert res["classes"].tolist() == val  # unprefixed: shared across iou types
                continue
            np.testing.assert_allclose(
                np.asarray(res[f"{iou_type}_{key}"], np.float64), np.asarray(val),
                atol=1e-6, err_msg=f"{iou_type}:{key}",
            )

    # near-linear scaling: 4x the images must cost < 6x the quarter-run compute
    # (vs the old <10x quadratic-only guard), with an absolute backstop
    ratio = compute_sec / quarter_sec
    assert ratio < 6.0, f"mAP compute scaling ratio 1.25k->5k imgs is {ratio:.1f} (superlinear)"
    assert compute_sec < 150.0, f"bbox+segm mAP compute at 5k imgs took {compute_sec:.1f}s"
