"""mAP correctness against COCOeval-semantics golden fixtures.

Round 2 only checked mAP against the reference's legacy pure-torch template on
fixtures crafted to avoid its known divergences from pycocotools. These tests
check the production evaluator against ``tests/_coco_oracle.py`` (an independent
per-cell-loop implementation of the COCOeval protocol) on UNrestricted inputs:
crowds, all area buckets, explicit area fields, score/IoU ties, dense overlaps,
custom maxDets and segm masks. Golden numbers are committed in
``tests/_data/coco_golden.json`` (regenerate with ``python tests/gen_coco_golden.py``).

Also locks in the round-3 matcher fix: the former ``.at[].set``-in-scan matcher
produced batch-size-dependent wrong matches for row batches >= 64 (an XLA
scatter miscompile, identical on CPU and TPU); the fuzz here runs the evaluator
on datasets large enough that any such batch dependence resurfaces.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from tests._coco_oracle import CocoOracle
from torchmetrics_tpu.detection import MeanAveragePrecision

_DATA = os.path.join(os.path.dirname(__file__), "_data", "coco_golden.json")

with open(_DATA) as f:
    GOLDEN = json.load(f)


def _unpack_sample(d):
    out = {}
    for k, v in d.items():
        if k == "masks":
            sent = v.index(-1)
            shape = tuple(v[sent + 1 :])
            packed = np.asarray(v[:sent], np.uint8)
            out[k] = np.unpackbits(packed, count=int(np.prod(shape))).reshape(shape).astype(bool)
        elif k in ("labels", "iscrowd"):
            out[k] = np.asarray(v, np.int32)
        else:
            out[k] = np.asarray(v, np.float32)
    return out


@pytest.mark.parametrize("name", list(GOLDEN))
def test_map_matches_cocoeval_golden(name):
    fx = GOLDEN[name]
    preds = [_unpack_sample(p) for p in fx["preds"]]
    target = [_unpack_sample(t) for t in fx["target"]]
    metric = MeanAveragePrecision(iou_type=fx["iou_type"], class_metrics=True, **fx["opts"])
    metric.update(preds, target)
    res = {k: np.asarray(v) for k, v in metric.compute().items()}
    for key, golden in fx["stats"].items():
        if key == "classes":
            assert res["classes"].tolist() == golden
            continue
        ours = np.asarray(res[key], np.float64)
        # f32 box coords in update vs f64 oracle: documented 1e-6 envelope; all
        # count-derived quantities are exact
        np.testing.assert_allclose(ours, np.asarray(golden), atol=1e-6, err_msg=f"{name}:{key}")


def _rand_dataset(rng, n_imgs, n_cls, dense=False):
    preds, target = [], []
    for _ in range(n_imgs):
        ng = int(rng.integers(0, 12))
        nd = int(rng.integers(0, 15))
        gt = np.concatenate([rng.uniform(0, 300, (ng, 2)), np.zeros((ng, 2))], -1).astype(np.float32)
        gt[:, 2:] = gt[:, :2] + rng.uniform(4, 250, (ng, 2))
        if dense and ng and nd:
            dt = gt[rng.integers(0, ng, nd)] + rng.uniform(-10, 10, (nd, 4)).astype(np.float32)
        else:
            dt = np.concatenate([rng.uniform(0, 300, (nd, 2)), np.zeros((nd, 2))], -1).astype(np.float32)
            dt[:, 2:] = dt[:, :2] + rng.uniform(4, 250, (nd, 2))
        preds.append({
            "boxes": dt.round(2),
            "scores": rng.choice([0.2, 0.5, 0.5, 0.8, 0.9], nd).astype(np.float32),
            "labels": rng.integers(0, n_cls, nd).astype(np.int32),
        })
        target.append({
            "boxes": gt.round(2),
            "labels": rng.integers(0, n_cls, ng).astype(np.int32),
            "iscrowd": (rng.random(ng) < 0.2).astype(np.int32),
            "area": np.where(rng.random(ng) < 0.3, rng.uniform(10, 20000, ng), 0).astype(np.float32),
        })
    return preds, target


@pytest.mark.parametrize("seed,n_imgs,n_cls,dense", [
    (0, 40, 3, True),    # > 64 rows per class: the old-matcher miscompile regime
    (1, 120, 2, True),   # hundreds of rows
    (2, 60, 6, False),
    (3, 10, 1, True),    # single class, everything in one row slice
])
def test_map_fuzz_vs_cocoeval_oracle(seed, n_imgs, n_cls, dense):
    rng = np.random.default_rng(seed)
    preds, target = _rand_dataset(rng, n_imgs, n_cls, dense)
    metric = MeanAveragePrecision(class_metrics=True)
    metric.update(preds, target)
    res = {k: np.asarray(v) for k, v in metric.compute().items()}
    golden = CocoOracle().stats(preds, target, class_metrics=True)
    for key, val in golden.items():
        if key == "classes":
            assert res["classes"].tolist() == val
            continue
        np.testing.assert_allclose(
            np.asarray(res[key], np.float64), np.asarray(val), atol=1e-6, err_msg=key
        )


def test_precision_recall_arrays_match_oracle_exactly():
    """extended_summary precision/recall tensors, not just the means."""
    rng = np.random.default_rng(7)
    preds, target = _rand_dataset(rng, 30, 2, dense=True)
    metric = MeanAveragePrecision(extended_summary=True)
    metric.update(preds, target)
    res = metric.compute()
    oracle_ev = CocoOracle().evaluate(preds, target)
    np.testing.assert_allclose(
        np.asarray(res["precision"], np.float64), oracle_ev["precision"], atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(res["recall"], np.float64), oracle_ev["recall"], atol=1e-6
    )
