"""InfoLM parity against the reference, through a REAL HF masked-LM pipeline.

No pretrained weights are downloadable here, so the oracle model is a tiny
randomly-initialized ``BertForMaskedLM`` + WordPiece tokenizer built locally and
saved to disk — both sides load it by path through their standard HF loaders, so
the full pipeline (tokenizer, masking loop, temperature softmax, idf weighting,
measure math) is exercised end to end, not just the measure formulas.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from tests.oracle import reference_torchmetrics

transformers = pytest.importorskip("transformers")

PREDS = [
    "the cat sat on the mat",
    "a quick brown fox jumps over a lazy dog",
    "deep nets learn representations",
    "he read the book because he was interested in world history",
]
TARGETS = [
    "the cat lay on the rug",
    "the quick brown fox jumped over the lazy dog",
    "neural networks learn features",
    "he was interested in world history because he read the book",
]

VOCAB = (
    "[PAD] [UNK] [CLS] [SEP] [MASK] the a cat sat lay on mat rug quick brown fox jumps "
    "jumped over lazy dog deep neural nets networks learn representations features he "
    "read book because was interested in world history".split()
)


@pytest.fixture(scope="module")
def tiny_mlm_dir(tmp_path_factory):
    import torch
    from transformers import BertConfig, BertForMaskedLM, BertTokenizer

    d = tmp_path_factory.mktemp("tiny_mlm")
    vocab_file = os.path.join(d, "vocab.txt")
    with open(vocab_file, "w") as f:
        f.write("\n".join(VOCAB))
    tokenizer = BertTokenizer(vocab_file)
    torch.manual_seed(0)
    config = BertConfig(
        vocab_size=len(VOCAB), hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
        intermediate_size=64, max_position_embeddings=32, max_length=20,
    )
    model = BertForMaskedLM(config)
    model.save_pretrained(d)
    tokenizer.save_pretrained(d)
    return str(d)


@pytest.mark.parametrize(
    "measure,alpha,beta",
    [
        ("kl_divergence", None, None),
        ("alpha_divergence", 0.5, None),
        ("beta_divergence", None, 0.7),
        ("ab_divergence", 0.25, 0.7),
        ("renyi_divergence", 0.3, None),
        ("l1_distance", None, None),
        ("l2_distance", None, None),
        ("l_infinity_distance", None, None),
        ("fisher_rao_distance", None, None),
    ],
)
@pytest.mark.parametrize("idf", [False, True])
def test_infolm_functional_vs_reference(tiny_mlm_dir, measure, alpha, beta, idf):
    tm = reference_torchmetrics()
    if tm is None:
        pytest.skip("reference torchmetrics unavailable")
    from torchmetrics.functional.text.infolm import infolm as ref_infolm

    from torchmetrics_tpu.functional.text import infolm

    ref = ref_infolm(
        PREDS, TARGETS, model_name_or_path=tiny_mlm_dir, information_measure=measure,
        idf=idf, alpha=alpha, beta=beta, verbose=False, return_sentence_level_score=True,
    )
    ours = infolm(
        PREDS, TARGETS, model_name_or_path=tiny_mlm_dir, information_measure=measure,
        idf=idf, alpha=alpha, beta=beta, verbose=False, return_sentence_level_score=True,
    )
    # The reference mis-unsorts its length-sorted batches (applies the sorting
    # permutation twice, helper_embedding_metric.py:79-84 + infolm.py:539-541); our
    # sentence scores are in input order. ref[i] == ours[s[s[i]]] with s the stable
    # length argsort (identical for PREDS/TARGETS here, so the pairing agrees).
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(tiny_mlm_dir, local_files_only=True)
    lengths = np.asarray(
        tok(PREDS, padding="max_length", max_length=20, truncation=True, return_tensors="np")[
            "attention_mask"
        ].sum(1)
    )
    s = np.argsort(lengths, kind="stable")
    ours_sentence = np.asarray(ours[1])
    # fisher_rao = 2*arccos(x) evaluated at x ~= 1 where arccos is infinitely
    # ill-conditioned (arccos(1-d) ~ sqrt(2d)): f32 noise at 1e-7 in the inner sum
    # legitimately moves the output by ~1e-3 on identical-distribution pairs
    atol = 2e-3 if measure == "fisher_rao_distance" else 2e-5
    np.testing.assert_allclose(np.asarray(ours[0]), ref[0].numpy(), atol=atol)
    np.testing.assert_allclose(ours_sentence[s][s], ref[1].numpy(), atol=atol)


def test_infolm_class_accumulates_and_syncs(tiny_mlm_dir):
    tm = reference_torchmetrics()
    if tm is None:
        pytest.skip("reference torchmetrics unavailable")
    from torchmetrics.text.infolm import InfoLM as RefInfoLM

    from torchmetrics_tpu.text import InfoLM

    ref = RefInfoLM(model_name_or_path=tiny_mlm_dir, idf=True, verbose=False)
    ours = InfoLM(model_name_or_path=tiny_mlm_dir, idf=True, verbose=False)
    for i in range(0, 4, 2):
        ref.update(PREDS[i : i + 2], TARGETS[i : i + 2])
        ours.update(PREDS[i : i + 2], TARGETS[i : i + 2])
    np.testing.assert_allclose(np.asarray(ours.compute()), ref.compute().numpy(), atol=2e-5)
    # merge_state across two shards == one-shot (idf is corpus-level, so this only
    # holds when states merge before compute — which is the point of the cat states)
    a = InfoLM(model_name_or_path=tiny_mlm_dir, idf=True, verbose=False)
    b = InfoLM(model_name_or_path=tiny_mlm_dir, idf=True, verbose=False)
    a.update(PREDS[:2], TARGETS[:2])
    b.update(PREDS[2:], TARGETS[2:])
    a.merge_state(b)
    np.testing.assert_allclose(np.asarray(a.compute()), ref.compute().numpy(), atol=2e-5)


def test_infolm_user_model_seam(tiny_mlm_dir):
    """A custom (non-HF-API) masked LM drives the same pipeline via model+tokenizer."""
    import torch
    from transformers import AutoModelForMaskedLM, AutoTokenizer

    from torchmetrics_tpu.functional.text import infolm

    tok = AutoTokenizer.from_pretrained(tiny_mlm_dir, local_files_only=True)
    hf = AutoModelForMaskedLM.from_pretrained(tiny_mlm_dir, local_files_only=True).eval()

    def forward(ids, mask):
        with torch.no_grad():
            return hf(torch.as_tensor(np.asarray(ids)), torch.as_tensor(np.asarray(mask))).logits.numpy()

    via_path = infolm(PREDS, TARGETS, model_name_or_path=tiny_mlm_dir, idf=False, max_length=20)
    via_seam = infolm(PREDS, TARGETS, model=forward, user_tokenizer=tok, idf=False, max_length=20)
    np.testing.assert_allclose(np.asarray(via_seam), np.asarray(via_path), atol=1e-6)


def test_infolm_measure_validation():
    from torchmetrics_tpu.functional.text.infolm import _InformationMeasure

    with pytest.raises(ValueError):
        _InformationMeasure("alpha_divergence", alpha=None)
    with pytest.raises(ValueError):
        _InformationMeasure("alpha_divergence", alpha=1.0)
    with pytest.raises(ValueError):
        _InformationMeasure("beta_divergence", beta=0.0)
    with pytest.raises(ValueError):
        _InformationMeasure("ab_divergence", alpha=0.5, beta=-0.5)
    with pytest.raises(ValueError):
        _InformationMeasure("renyi_divergence", alpha=1.0)
    with pytest.raises(ValueError):
        _InformationMeasure("not_a_measure")
