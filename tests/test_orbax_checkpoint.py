"""Orbax checkpoint round-trips (VERDICT r4 #4).

`metric.py:24` and `docs/core.md` claim the state pytree can be handed to orbax
as-is; these tests back the claim with save→restore→compute equality through
`orbax.checkpoint` for every state shape the framework produces: tensor states,
dynamic cat states, the fused-collection state, wrapper trees (children +
wrapper-level extrema), the padded detection accumulator, and a sharded state on
the 8-device CPU mesh (reference resume semantics: metric.py:919-990).
"""

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchmetrics_tpu import MetricCollection
from torchmetrics_tpu.classification import (
    MulticlassAccuracy,
    MulticlassAUROC,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
)
from torchmetrics_tpu.detection import MeanAveragePrecision
from torchmetrics_tpu.detection.sharded import PaddedDetectionAccumulator
from torchmetrics_tpu.regression import SpearmanCorrCoef
from torchmetrics_tpu.wrappers import BootStrapper, MinMaxMetric

from conftest import seed_all


def _roundtrip(tmp_path, tree, abstract=None):
    """Save a pytree through orbax and load it back (fresh checkpointer each way)."""
    path = tmp_path / "ckpt"
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, abstract) if abstract is not None else ckptr.restore(path)


def test_stat_scores_metric_roundtrip(tmp_path):
    rng = seed_all()
    metric = MulticlassAccuracy(num_classes=5, average="macro")
    for _ in range(3):
        metric.update(
            jnp.asarray(rng.normal(size=(32, 5)).astype(np.float32)),
            jnp.asarray(rng.integers(0, 5, 32, dtype=np.int32)),
        )
    expected = np.asarray(metric.compute())

    metric.persistent(True)
    restored_sd = _roundtrip(tmp_path, metric.state_dict())
    fresh = MulticlassAccuracy(num_classes=5, average="macro")
    fresh.load_state_dict(restored_sd)
    assert fresh._update_count == metric._update_count
    np.testing.assert_allclose(np.asarray(fresh.compute()), expected, atol=1e-8)


def test_cat_state_metric_roundtrip(tmp_path):
    rng = seed_all(7)
    metric = SpearmanCorrCoef()
    for _ in range(4):
        metric.update(
            jnp.asarray(rng.normal(size=17).astype(np.float32)),
            jnp.asarray(rng.normal(size=17).astype(np.float32)),
        )
    expected = np.asarray(metric.compute())

    metric.persistent(True)
    restored_sd = _roundtrip(tmp_path, metric.state_dict())
    fresh = SpearmanCorrCoef()
    fresh.load_state_dict(restored_sd)
    np.testing.assert_allclose(np.asarray(fresh.compute()), expected, atol=1e-7)


def test_fresh_checkpoint_keeps_no_update_warning(tmp_path):
    """A checkpoint saved before any update must not mark the restored metric
    as updated (exact-count semantics, round-4 commit 1475a36)."""
    fresh_src = MulticlassAccuracy(num_classes=5)
    fresh_src.persistent(True)
    restored_sd = _roundtrip(tmp_path, fresh_src.state_dict())
    fresh = MulticlassAccuracy(num_classes=5)
    fresh.load_state_dict(restored_sd)
    assert fresh._update_count == 0


def test_fused_collection_state_roundtrip(tmp_path):
    rng = seed_all(3)
    collection = MetricCollection({
        "acc": MulticlassAccuracy(5, average="micro", validate_args=False),
        "f1": MulticlassF1Score(5, average="macro", validate_args=False),
        "auroc": MulticlassAUROC(5, thresholds=50, validate_args=False),
        "confmat": MulticlassConfusionMatrix(5, validate_args=False),
    })
    pure = collection.as_pure()
    states = pure.init()
    step = jax.jit(pure.update)
    for _ in range(3):
        probs = jax.nn.softmax(jnp.asarray(rng.normal(size=(64, 5)).astype(np.float32)))
        target = jnp.asarray(rng.integers(0, 5, 64, dtype=np.int32))
        states = step(states, probs, target)
    expected = {k: np.asarray(v) for k, v in jax.jit(pure.compute)(states).items()}

    restored = _roundtrip(tmp_path, jax.tree.map(np.asarray, states))
    values = jax.jit(pure.compute)(jax.tree.map(jnp.asarray, restored))
    for key, want in expected.items():
        np.testing.assert_allclose(np.asarray(values[key]), want, atol=1e-8, err_msg=key)


@pytest.mark.parametrize("wrapper_kind", ["bootstrapper", "minmax"])
def test_wrapper_roundtrip(tmp_path, wrapper_kind):
    rng = seed_all(11)
    if wrapper_kind == "bootstrapper":
        wrapper = BootStrapper(
            MulticlassAccuracy(num_classes=4, average="micro"),
            num_bootstraps=5, sampling_strategy="multinomial", seed=0, raw=True,
        )
        fresh = BootStrapper(
            MulticlassAccuracy(num_classes=4, average="micro"),
            num_bootstraps=5, sampling_strategy="multinomial", seed=0, raw=True,
        )
    else:
        wrapper = MinMaxMetric(MulticlassAccuracy(num_classes=4, average="micro"))
        fresh = MinMaxMetric(MulticlassAccuracy(num_classes=4, average="micro"))
    for _ in range(3):
        preds = jnp.asarray(rng.normal(size=(24, 4)).astype(np.float32))
        target = jnp.asarray(rng.integers(0, 4, 24, dtype=np.int32))
        if wrapper_kind == "minmax":
            wrapper(preds, target)  # MinMax tracks extrema through forward
        else:
            wrapper.update(preds, target)
    expected = jax.tree.map(np.asarray, wrapper.compute())

    wrapper.persistent(True)
    restored_sd = _roundtrip(tmp_path, wrapper.state_dict())
    fresh.load_state_dict(restored_sd)
    got = jax.tree.map(np.asarray, fresh.compute())
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-8), got, expected)


@pytest.mark.parametrize("strategy", ["multinomial", "poisson"])
def test_bootstrapper_roundtrip_both_paths(tmp_path, strategy):
    """Checkpoint contents must not depend on the internal fast-path predicate:
    the vmapped stacked-state path and the per-replica list path both persist
    their accumulation (review finding r5)."""
    rng = seed_all(13)
    def fresh():
        return BootStrapper(
            MulticlassAccuracy(num_classes=3, average="micro"),
            num_bootstraps=4, sampling_strategy=strategy, seed=3,
        )
    wrapper = fresh()
    wrapper.update(
        jnp.asarray(rng.normal(size=(40, 3)).astype(np.float32)),
        jnp.asarray(rng.integers(0, 3, 40, dtype=np.int32)),
    )
    expected = jax.tree.map(np.asarray, wrapper.compute())
    wrapper.persistent(True)
    restored_sd = _roundtrip(tmp_path, wrapper.state_dict())
    loaded = fresh()
    loaded.load_state_dict(restored_sd)
    got = jax.tree.map(np.asarray, loaded.compute())
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-8), got, expected)


def test_running_wrapper_roundtrip(tmp_path):
    from torchmetrics_tpu.aggregation import SumMetric
    from torchmetrics_tpu.wrappers import Running

    metric = Running(SumMetric(), window=2)
    for v in (1.0, 2.0, 3.0):
        metric.update(v)
    expected = float(metric.compute())  # last-2 window: 5.0
    metric.persistent(True)
    restored_sd = _roundtrip(tmp_path, metric.state_dict())
    loaded = Running(SumMetric(), window=2)
    loaded.load_state_dict(restored_sd)
    assert float(loaded.compute()) == expected
    loaded.update(4.0)  # the window keeps sliding after resume
    assert float(loaded.compute()) == 7.0


def test_default_persistence_wrapper_saves_nothing():
    """Without persistent(True) a wrapper's state_dict is empty and a restore
    leaves the target cleanly fresh — never an 'updated' wrapper with empty
    children (review finding r5: partial checkpoints corrupted compute)."""
    wrapper = MinMaxMetric(MulticlassAccuracy(num_classes=3, average="micro"))
    wrapper(jnp.asarray([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1]]), jnp.asarray([0, 1]))
    sd = wrapper.state_dict()
    assert sd == {}
    loaded = MinMaxMetric(MulticlassAccuracy(num_classes=3, average="micro"))
    loaded.load_state_dict(sd)
    assert loaded._update_count == 0


def _random_padded_batch(rng, acc, n_imgs):
    d, g = acc.max_detections, acc.max_groundtruths
    det_counts = rng.integers(1, d, n_imgs).astype(np.int32)
    gt_counts = rng.integers(1, g, n_imgs).astype(np.int32)
    xy = rng.uniform(0, 300, (n_imgs, d, 2)).astype(np.float32)
    wh = rng.uniform(10, 100, (n_imgs, d, 2)).astype(np.float32)
    gxy = rng.uniform(0, 300, (n_imgs, g, 2)).astype(np.float32)
    gwh = rng.uniform(10, 100, (n_imgs, g, 2)).astype(np.float32)
    gt_area = (gwh[..., 0] * gwh[..., 1]).astype(np.float32)
    return (
        np.concatenate([xy, xy + wh], -1), rng.uniform(0, 1, (n_imgs, d)).astype(np.float32),
        rng.integers(0, 6, (n_imgs, d)).astype(np.int32), det_counts,
        np.concatenate([gxy, gxy + gwh], -1), rng.integers(0, 6, (n_imgs, g)).astype(np.int32),
        np.zeros((n_imgs, g), np.int32), gt_area, gt_counts,
    )


def test_padded_detection_accumulator_roundtrip(tmp_path):
    rng = seed_all(5)
    acc = PaddedDetectionAccumulator(capacity_images=8, max_detections=12, max_groundtruths=9)
    state = acc.init()
    update = jax.jit(acc.update)
    for _ in range(2):
        state = update(state, *[jnp.asarray(a) for a in _random_padded_batch(rng, acc, 4)])

    restored = _roundtrip(tmp_path, jax.tree.map(np.asarray, state))
    for key, want in state.items():
        np.testing.assert_array_equal(np.asarray(restored[key]), np.asarray(want), err_msg=key)

    def _map_of(s):
        metric = MeanAveragePrecision()
        metric.update(*acc.to_lists(s))
        return float(metric.compute()["map"])

    assert _map_of(restored) == _map_of(state)


def test_sharded_state_roundtrip(tmp_path):
    """Sharded save→restore on the 8-device CPU mesh: the accumulator state is
    sharded over its image axis, checkpointed, restored back onto the SAME
    shardings via an abstract target, and produces an identical mAP."""
    rng = seed_all(9)
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    acc = PaddedDetectionAccumulator(capacity_images=16, max_detections=10, max_groundtruths=8)
    state = acc.init()
    update = jax.jit(acc.update)
    state = update(state, *[jnp.asarray(a) for a in _random_padded_batch(rng, acc, 16)])

    def shard_spec(v):
        return NamedSharding(mesh, P("dp", *([None] * (v.ndim - 1))) if v.ndim >= 1 and v.shape[0] % 8 == 0 else P())

    sharded = {k: jax.device_put(v, shard_spec(v)) for k, v in state.items()}
    abstract = {k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=v.sharding) for k, v in sharded.items()}

    restored = _roundtrip(tmp_path, sharded, abstract=abstract)
    for key, v in restored.items():
        assert v.sharding == sharded[key].sharding, key
        np.testing.assert_array_equal(np.asarray(v), np.asarray(state[key]), err_msg=key)

    before = MeanAveragePrecision()
    before.update(*acc.to_lists(state))
    after = MeanAveragePrecision()
    after.update(*acc.to_lists(restored))
    assert float(before.compute()["map"]) == float(after.compute()["map"])
