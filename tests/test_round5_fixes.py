"""Round-5 regression pins.

1. `feature_network` declarations: FeatureShare's documented use case ("FID+KID+IS
   run one extractor forward per batch") silently required an attribute no
   in-tree metric declared — the wrapper raised on the real classes. Pin the
   declarations AND the actual sharing (extractor called once per update).
2. The FID fused path must NOT engage through a NetworkCache-wrapped extractor
   (type-level probe): a FeatureShare'd FID goes through the shared cache.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.image import (
    FrechetInceptionDistance,
    InceptionScore,
    KernelInceptionDistance,
)
from torchmetrics_tpu.wrappers import FeatureShare


class CountingExtractor:
    num_features = 8

    def __init__(self):
        self.calls = 0

    def __call__(self, imgs):
        self.calls += 1
        return jnp.asarray(imgs).reshape(imgs.shape[0], -1)[:, :8].astype(jnp.float32)


def test_feature_share_dedupes_real_generative_metrics():
    ext = CountingExtractor()
    fs = FeatureShare([
        FrechetInceptionDistance(feature=ext),
        KernelInceptionDistance(feature=ext, subset_size=2),
        InceptionScore(feature=ext),
    ])
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.integers(0, 255, (2, 3, 8, 8)).astype(np.uint8))
    fs.update(imgs, real=True)
    assert ext.calls == 1, f"extractor ran {ext.calls}x for one shared update"
    fs.update(jnp.asarray(rng.integers(0, 255, (2, 3, 8, 8)).astype(np.uint8)), real=False)
    assert ext.calls == 2
    out = fs.compute()
    assert {"FrechetInceptionDistance", "KernelInceptionDistance", "InceptionScore"} <= set(out)


def test_feature_network_declared_on_model_backed_metrics():
    from torchmetrics_tpu.image.generative import (
        FrechetInceptionDistance as FID,
        InceptionScore as IS,
        KernelInceptionDistance as KID,
        MemorizationInformedFrechetInceptionDistance as MiFID,
    )
    from torchmetrics_tpu.image.lpip import LearnedPerceptualImagePatchSimilarity as LPIPS
    from torchmetrics_tpu.multimodal.clip_iqa import CLIPImageQualityAssessment as CLIPIQA
    from torchmetrics_tpu.multimodal.clip_score import CLIPScore

    assert FID.feature_network == "inception"
    assert KID.feature_network == "inception"
    assert IS.feature_network == "inception"
    assert MiFID.feature_network == "inception"
    assert LPIPS.feature_network == "net"
    assert CLIPIQA.feature_network == "model"
    assert CLIPScore.feature_network == "model"


def test_feature_share_stock_inception_normalize_numpy_input():
    """The review-found hole: with the stock Inception extractor, normalize=True
    (and/or numpy inputs) each member used to quantize/convert a PRIVATE copy,
    re-keying the id-based cache — the trunk silently ran once per member. The
    normalize flag now rides through the shared call, keyed on the caller's
    original buffer: ONE trunk forward per batch."""
    from torchmetrics_tpu.image._extractors import InceptionV3Features

    ext = InceptionV3Features(compute_dtype="float32")
    calls = {"n": 0}
    orig_apply = ext._apply

    def counting_apply(imgs):
        calls["n"] += 1
        return orig_apply(imgs)

    ext._apply = counting_apply
    fs = FeatureShare([
        FrechetInceptionDistance(feature=ext, normalize=True),
        KernelInceptionDistance(feature=ext, normalize=True, subset_size=2),
    ])
    rng = np.random.default_rng(1)
    imgs_np = rng.random((2, 3, 16, 16)).astype(np.float32)  # numpy, [0,1] floats
    fs.update(imgs_np, real=True)
    assert calls["n"] == 1, f"trunk ran {calls['n']}x for one shared normalize=True update"


def test_classwise_wrapper_labels_index_by_class_id():
    """User labels are indexed by OBSERVED class id, not position: with sparse
    observed classes {1, 2}, labels[1]/labels[2] must be used (a positional zip
    would attribute class 1's value to labels[0])."""
    from torchmetrics_tpu.detection import MeanAveragePrecision
    from torchmetrics_tpu.wrappers import ClasswiseWrapper

    preds = [{
        "boxes": jnp.asarray([[10.0, 10.0, 50.0, 50.0], [60.0, 60.0, 90.0, 90.0]]),
        "scores": jnp.asarray([0.9, 0.8]),
        "labels": jnp.asarray([1, 2]),
    }]
    target = [{
        "boxes": jnp.asarray([[10.0, 10.0, 50.0, 50.0], [60.0, 60.0, 90.0, 90.0]]),
        "labels": jnp.asarray([1, 2]),
    }]
    wrapped = ClasswiseWrapper(MeanAveragePrecision(class_metrics=True), labels=["zero", "one", "two"])
    wrapped.update(preds, target)
    out = wrapped.compute()
    keys = set(out)
    assert "meanaverageprecision_map_one" in keys and "meanaverageprecision_map_two" in keys
    assert "meanaverageprecision_map_zero" not in keys  # class 0 never observed
    # too-few labels for the observed ids raises instead of mislabeling
    import pytest as _pytest

    short = ClasswiseWrapper(MeanAveragePrecision(class_metrics=True), labels=["only", "two_labels"])
    short.update(preds, target)
    with _pytest.raises(ValueError, match="class id"):
        short.compute()
