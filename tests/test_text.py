"""Text tower parity tests vs the reference oracle (pure-python text metrics all run
without optional deps; rougeLsum needs the punkt download, so it is tested against
hand values with our offline fallback splitter instead)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from tests.helpers import _assert_allclose
from tests.oracle import reference_torchmetrics

import torchmetrics_tpu as tm
import torchmetrics_tpu.functional as F

PREDS_A = ["this is the prediction", "there is an other sample"]
TARGET_A = ["this is the reference", "there is another one"]
PREDS_B = ["hello there general kenobi", "foo bar foobar"]
TARGET_B = [["hello there general kenobi", "hello there!"], ["foo bar foobar", "foo bar foobar!"]]

CORPUS_PREDS = [
    "the cat is on the mat",
    "a quick brown fox jumps over the lazy dog",
    "It is a guide to action which ensures that the military always obeys the commands of the party",
]
CORPUS_TARGET = [
    ["there is a cat on the mat", "a cat is on the mat"],
    ["the quick brown fox jumps over a lazy dog"],
    [
        "It is a guide to action that ensures that the military will forever heed Party commands",
        "It is the guiding principle which guarantees the military forces always being under the command of the Party",
    ],
]


def _oracle():
    tm_ref = reference_torchmetrics()
    if tm_ref is None:
        pytest.skip("oracle unavailable")
    return tm_ref


ASR_CASES = [
    ("char_error_rate", "CharErrorRate"),
    ("word_error_rate", "WordErrorRate"),
    ("match_error_rate", "MatchErrorRate"),
    ("word_information_lost", "WordInfoLost"),
    ("word_information_preserved", "WordInfoPreserved"),
]


@pytest.mark.parametrize("fn_name,cls_name", ASR_CASES, ids=[c[0] for c in ASR_CASES])
def test_asr_metrics_parity(fn_name, cls_name):
    tm_ref = _oracle()
    ours = getattr(F, fn_name)(PREDS_A, TARGET_A)
    ref = getattr(tm_ref.functional.text, fn_name)(PREDS_A, TARGET_A)
    _assert_allclose(ours, ref.numpy(), atol=1e-5)
    ours_m = getattr(tm, cls_name)()
    ref_m = getattr(tm_ref.text, cls_name)()
    for p, t in ((PREDS_A, TARGET_A), (PREDS_B[0], TARGET_B[0][0])):
        ours_m.update(p, t)
        ref_m.update(p, t)
    _assert_allclose(ours_m.compute(), ref_m.compute().numpy(), atol=1e-5)


@pytest.mark.parametrize("n_gram", [2, 4])
@pytest.mark.parametrize("smooth", [False, True])
def test_bleu_parity(n_gram, smooth):
    tm_ref = _oracle()
    ours = F.bleu_score(CORPUS_PREDS, CORPUS_TARGET, n_gram=n_gram, smooth=smooth)
    ref = tm_ref.functional.text.bleu_score(CORPUS_PREDS, CORPUS_TARGET, n_gram=n_gram, smooth=smooth)
    _assert_allclose(ours, ref.numpy(), atol=1e-5)
    ours_m = tm.BLEUScore(n_gram=n_gram, smooth=smooth)
    ref_m = tm_ref.text.BLEUScore(n_gram=n_gram, smooth=smooth)
    for i in range(len(CORPUS_PREDS)):
        ours_m.update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
        ref_m.update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
    _assert_allclose(ours_m.compute(), ref_m.compute().numpy(), atol=1e-5)


@pytest.mark.parametrize("tokenize", ["none", "13a", "char", "intl", "zh"])
def test_sacre_bleu_parity(tokenize):
    tm_ref = _oracle()
    preds = ["The cat, is on the mat!", "Hello — wörld 123."]
    target = [["There is a cat on the mat."], ["Hello wörld, 1-2-3!"]]
    ours = F.sacre_bleu_score(preds, target, tokenize=tokenize, lowercase=True)
    ref = tm_ref.functional.text.sacre_bleu_score(preds, target, tokenize=tokenize, lowercase=True)
    _assert_allclose(ours, ref.numpy(), atol=1e-5)
    ours_m = tm.SacreBLEUScore(tokenize=tokenize)
    ref_m = tm_ref.text.SacreBLEUScore(tokenize=tokenize)
    ours_m.update(CORPUS_PREDS, CORPUS_TARGET)
    ref_m.update(CORPUS_PREDS, CORPUS_TARGET)
    _assert_allclose(ours_m.compute(), ref_m.compute().numpy(), atol=1e-5)


@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
@pytest.mark.parametrize("substitution_cost", [1, 2])
def test_edit_distance_parity(reduction, substitution_cost):
    tm_ref = _oracle()
    ours = F.edit_distance(PREDS_A, TARGET_A, substitution_cost=substitution_cost, reduction=reduction)
    ref = tm_ref.functional.text.edit_distance(
        PREDS_A, TARGET_A, substitution_cost=substitution_cost, reduction=reduction
    )
    _assert_allclose(ours, ref.numpy(), atol=1e-6)
    ours_m = tm.EditDistance(substitution_cost=substitution_cost, reduction=reduction)
    ref_m = tm_ref.text.EditDistance(substitution_cost=substitution_cost, reduction=reduction)
    ours_m.update(PREDS_A, TARGET_A)
    ours_m.update(PREDS_B, [t[0] for t in TARGET_B])
    ref_m.update(PREDS_A, TARGET_A)
    ref_m.update(PREDS_B, [t[0] for t in TARGET_B])
    _assert_allclose(ours_m.compute(), ref_m.compute().numpy(), atol=1e-6)


@pytest.mark.parametrize("n_word_order", [0, 2])
@pytest.mark.parametrize("whitespace", [False, True])
def test_chrf_parity(n_word_order, whitespace):
    tm_ref = _oracle()
    kwargs = dict(n_word_order=n_word_order, whitespace=whitespace)
    ours = F.chrf_score(CORPUS_PREDS, CORPUS_TARGET, **kwargs)
    ref = tm_ref.functional.text.chrf_score(CORPUS_PREDS, CORPUS_TARGET, **kwargs)
    _assert_allclose(ours, ref.numpy(), atol=1e-5)
    ours_m = tm.CHRFScore(return_sentence_level_score=True, **kwargs)
    ref_m = tm_ref.text.CHRFScore(return_sentence_level_score=True, **kwargs)
    for i in range(len(CORPUS_PREDS)):
        ours_m.update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
        ref_m.update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
    ours_score, ours_sent = ours_m.compute()
    ref_score, ref_sent = ref_m.compute()
    _assert_allclose(ours_score, ref_score.numpy(), atol=1e-5)
    _assert_allclose(ours_sent, ref_sent.numpy(), atol=1e-5)


def test_squad_parity():
    tm_ref = _oracle()
    preds = [{"prediction_text": "1976", "id": "id1"}, {"prediction_text": "the big apple", "id": "id2"}]
    target = [
        {"answers": {"answer_start": [97], "text": ["1976"]}, "id": "id1"},
        {"answers": {"answer_start": [1], "text": ["New York City", "the big apple!"]}, "id": "id2"},
    ]
    ours = F.squad(preds, target)
    ref = tm_ref.functional.text.squad(preds, target)
    _assert_allclose({k: np.asarray(v) for k, v in ours.items()}, {k: v.numpy() for k, v in ref.items()}, atol=1e-4)
    ours_m = tm.SQuAD()
    ref_m = tm_ref.text.SQuAD()
    ours_m.update(preds, target)
    ref_m.update(preds, target)
    _assert_allclose(
        {k: np.asarray(v) for k, v in ours_m.compute().items()},
        {k: v.numpy() for k, v in ref_m.compute().items()},
        atol=1e-4,
    )


@pytest.mark.parametrize("ignore_index", [None, 1])
def test_perplexity_parity(ignore_index):
    tm_ref = _oracle()
    import torch

    rng = np.random.default_rng(5)
    preds = rng.normal(size=(2, 8, 5)).astype(np.float32)
    target = rng.integers(0, 5, (2, 8))
    ours = F.perplexity(jnp.asarray(preds), jnp.asarray(target), ignore_index=ignore_index)
    ref = tm_ref.functional.text.perplexity(
        torch.as_tensor(preds), torch.as_tensor(target).long(), ignore_index=ignore_index
    )
    _assert_allclose(ours, ref.numpy(), atol=1e-4)
    ours_m = tm.Perplexity(ignore_index=ignore_index)
    ref_m = tm_ref.text.Perplexity(ignore_index=ignore_index)
    for i in range(2):
        ours_m.update(jnp.asarray(preds[i : i + 1]), jnp.asarray(target[i : i + 1]))
        ref_m.update(torch.as_tensor(preds[i : i + 1]), torch.as_tensor(target[i : i + 1]).long())
    _assert_allclose(ours_m.compute(), ref_m.compute().numpy(), atol=1e-4)


@pytest.mark.parametrize("accumulate", ["best", "avg"])
@pytest.mark.parametrize("use_stemmer", [False, True])
def test_rouge_parity_no_lsum(accumulate, use_stemmer):
    tm_ref = _oracle()
    keys = ("rouge1", "rouge2", "rougeL")
    ours = F.rouge_score(CORPUS_PREDS, CORPUS_TARGET, accumulate=accumulate, use_stemmer=use_stemmer, rouge_keys=keys)
    ref = tm_ref.functional.text.rouge_score(
        CORPUS_PREDS, CORPUS_TARGET, accumulate=accumulate, use_stemmer=use_stemmer, rouge_keys=keys
    )
    _assert_allclose({k: np.asarray(v) for k, v in ours.items()}, {k: v.numpy() for k, v in ref.items()}, atol=1e-5)
    ours_m = tm.ROUGEScore(accumulate=accumulate, use_stemmer=use_stemmer, rouge_keys=keys)
    ref_m = tm_ref.text.ROUGEScore(accumulate=accumulate, use_stemmer=use_stemmer, rouge_keys=keys)
    for i in range(len(CORPUS_PREDS)):
        ours_m.update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
        ref_m.update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
    _assert_allclose(
        {k: np.asarray(v) for k, v in ours_m.compute().items()},
        {k: v.numpy() for k, v in ref_m.compute().items()},
        atol=1e-5,
    )


def test_rouge_lsum_offline_fallback():
    # single-sentence inputs: Lsum == L regardless of the splitter
    res = F.rouge_score("My name is John", "Is your name John", rouge_keys=("rougeL", "rougeLsum"))
    assert float(res["rougeLsum_fmeasure"]) == pytest.approx(float(res["rougeL_fmeasure"]))
    # multi-sentence smoke with the regex fallback splitter
    res2 = F.rouge_score(
        "The cat sat. The dog ran!", "A cat sat. A dog ran!", rouge_keys=("rougeLsum",)
    )
    assert 0.0 < float(res2["rougeLsum_fmeasure"]) <= 1.0


def test_text_merge_matches_single():
    single = tm.BLEUScore()
    shards = [tm.BLEUScore() for _ in range(3)]
    for i in range(3):
        single.update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
        shards[i].update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
    shards[0].merge_state(shards[1])
    shards[0].merge_state(shards[2])
    _assert_allclose(shards[0].compute(), single.compute(), atol=1e-6)

    single = tm.WordErrorRate()
    shards = [tm.WordErrorRate() for _ in range(2)]
    for i, (p, t) in enumerate(zip(PREDS_A, TARGET_A)):
        single.update([p], [t])
        shards[i].update([p], [t])
    shards[0].merge_state(shards[1])
    _assert_allclose(shards[0].compute(), single.compute(), atol=1e-6)


def test_text_validation_errors():
    with pytest.raises(ValueError, match="Corpus has different size"):
        F.bleu_score(["a", "b"], [["a"]])
    with pytest.raises(ValueError, match="`tokenize`"):
        tm.SacreBLEUScore(tokenize="bogus")
    with pytest.raises(ValueError, match="same length"):
        F.edit_distance(["a"], ["a", "b"])
    with pytest.raises(KeyError, match="prediction_text"):
        F.squad({"wrong": "x"}, {"answers": {"text": ["y"]}, "id": "1"})
    with pytest.raises(ValueError, match="3 dimensions"):
        F.perplexity(jnp.zeros((2, 3)), jnp.zeros((2, 3), jnp.int32))
    with pytest.raises(ValueError, match="unknown rouge key"):
        F.rouge_score("a", "a", rouge_keys=("rougeX",))


@pytest.mark.parametrize("normalize", [False, True])
@pytest.mark.parametrize("no_punctuation", [False, True])
def test_ter_parity(normalize, no_punctuation):
    tm_ref = _oracle()
    kwargs = dict(normalize=normalize, no_punctuation=no_punctuation, return_sentence_level_score=True)
    ours, ours_sent = F.translation_edit_rate(CORPUS_PREDS, CORPUS_TARGET, **kwargs)
    ref, ref_sent = tm_ref.functional.text.translation_edit_rate(CORPUS_PREDS, CORPUS_TARGET, **kwargs)
    _assert_allclose(ours, ref.numpy(), atol=1e-5)
    _assert_allclose(ours_sent, np.asarray([float(s) for s in ref_sent]), atol=1e-5)
    ours_m = tm.TranslationEditRate(normalize=normalize, no_punctuation=no_punctuation)
    ref_m = tm_ref.text.TranslationEditRate(normalize=normalize, no_punctuation=no_punctuation)
    for i in range(len(CORPUS_PREDS)):
        ours_m.update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
        ref_m.update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
    _assert_allclose(ours_m.compute(), ref_m.compute().numpy(), atol=1e-5)


def test_eed_parity():
    tm_ref = _oracle()
    ours, ours_sent = F.extended_edit_distance(CORPUS_PREDS, CORPUS_TARGET, return_sentence_level_score=True)
    ref, ref_sent = tm_ref.functional.text.extended_edit_distance(
        CORPUS_PREDS, CORPUS_TARGET, return_sentence_level_score=True
    )
    _assert_allclose(ours, ref.numpy(), atol=1e-5)
    _assert_allclose(ours_sent, np.asarray([float(s) for s in ref_sent]), atol=1e-5)
    ours_m = tm.ExtendedEditDistance()
    ref_m = tm_ref.text.ExtendedEditDistance()
    for i in range(len(CORPUS_PREDS)):
        ours_m.update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
        ref_m.update([CORPUS_PREDS[i]], [CORPUS_TARGET[i]])
    _assert_allclose(ours_m.compute(), ref_m.compute().numpy(), atol=1e-5)


def test_ter_shifting_case():
    # a case that requires a block shift: "b c a" -> "a b c" is 1 shift = 1 edit
    score = F.translation_edit_rate(["b c a"], [["a b c"]])
    assert float(score) == pytest.approx(1.0 / 3.0)


def test_eed_rounding_tie_breaks_match_reference():
    tm_ref = _oracle()
    # adversarial repeated-token sentences that produce equal-cost DP cells
    hyp = ["hello ! don't on is ? Dr. hello !"]
    ref = ["big small the fast , runs don't end . hello ! dog big fast , big"]
    ours = F.extended_edit_distance(hyp, [ref])
    expected = tm_ref.functional.text.extended_edit_distance(hyp, [ref])
    _assert_allclose(ours, expected.numpy(), atol=1e-7)


def test_edit_distance_beam_matches_reference():
    tm_ref = _oracle()
    preds = ["cat U.S. runs"]
    target = ["Dr. is cat very blue ? very dog blue mat big a U.S."]
    for sc in (1, 2):
        ours = F.edit_distance(preds, target, substitution_cost=sc)
        expected = tm_ref.functional.text.edit_distance(preds, target, substitution_cost=sc)
        _assert_allclose(ours, expected.numpy(), atol=1e-7)
