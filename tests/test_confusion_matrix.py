"""Confusion matrix vs sklearn (reference tests/unittests/classification/test_confusion_matrix.py)."""

from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn import metrics as sk

import torchmetrics_tpu.functional as F
from torchmetrics_tpu.classification import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from conftest import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES, THRESHOLD, seed_all
from helpers import MetricTester

_rng = seed_all(11)
_bin_preds = _rng.random((NUM_BATCHES, BATCH_SIZE)).astype(np.float32)
_bin_target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE))
_mc_preds = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
_mc_target = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
_ml_preds = _rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32)
_ml_target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))


def _sk_bin_cm(preds, target):
    return sk.confusion_matrix(target, (preds >= THRESHOLD).astype(int), labels=[0, 1])


def _sk_mc_cm(preds, target):
    return sk.confusion_matrix(target, preds, labels=list(range(NUM_CLASSES)))


def _sk_ml_cm(preds, target):
    return sk.multilabel_confusion_matrix(
        target.reshape(-1, NUM_CLASSES), (preds >= THRESHOLD).astype(int).reshape(-1, NUM_CLASSES)
    )


class TestBinaryConfusionMatrix(MetricTester):
    def test_functional(self):
        self.run_functional_metric_test(_bin_preds, _bin_target, F.binary_confusion_matrix, _sk_bin_cm)

    def test_class(self):
        self.run_class_metric_test(_bin_preds, _bin_target, BinaryConfusionMatrix, _sk_bin_cm)

    def test_merge(self):
        self.run_merge_state_test(_bin_preds, _bin_target, BinaryConfusionMatrix, _sk_bin_cm)

    def test_ingraph(self):
        self.run_ingraph_sharded_test(_bin_preds, _bin_target, BinaryConfusionMatrix, _sk_bin_cm)


class TestMulticlassConfusionMatrix(MetricTester):
    def test_functional(self):
        self.run_functional_metric_test(
            _mc_preds, _mc_target, partial(F.multiclass_confusion_matrix, num_classes=NUM_CLASSES), _sk_mc_cm
        )

    def test_class(self):
        self.run_class_metric_test(
            _mc_preds, _mc_target, MulticlassConfusionMatrix, _sk_mc_cm, {"num_classes": NUM_CLASSES}
        )

    def test_merge(self):
        self.run_merge_state_test(
            _mc_preds, _mc_target, MulticlassConfusionMatrix, _sk_mc_cm, {"num_classes": NUM_CLASSES}
        )

    def test_ingraph(self):
        self.run_ingraph_sharded_test(
            _mc_preds, _mc_target, MulticlassConfusionMatrix, _sk_mc_cm, {"num_classes": NUM_CLASSES}
        )


class TestMultilabelConfusionMatrix(MetricTester):
    def test_functional(self):
        self.run_functional_metric_test(
            _ml_preds, _ml_target, partial(F.multilabel_confusion_matrix, num_labels=NUM_CLASSES), _sk_ml_cm
        )

    def test_class(self):
        self.run_class_metric_test(
            _ml_preds, _ml_target, MultilabelConfusionMatrix, _sk_ml_cm, {"num_labels": NUM_CLASSES}
        )


@pytest.mark.parametrize("normalize", ["true", "pred", "all"])
def test_normalization(normalize):
    ours = np.asarray(
        F.multiclass_confusion_matrix(
            jnp.asarray(_mc_preds[0]), jnp.asarray(_mc_target[0]), num_classes=NUM_CLASSES, normalize=normalize
        )
    )
    ref = sk.confusion_matrix(
        _mc_target[0], _mc_preds[0], labels=list(range(NUM_CLASSES)), normalize=normalize
    )
    np.testing.assert_allclose(ours, ref, atol=1e-6)


def test_confusion_matrix_ignore_index():
    target = np.array([0, 1, -1, 2])
    preds = np.array([0, 1, 2, 2])
    cm = np.asarray(
        F.multiclass_confusion_matrix(jnp.asarray(preds), jnp.asarray(target), num_classes=3, ignore_index=-1)
    )
    expected = np.array([[1, 0, 0], [0, 1, 0], [0, 0, 1]])
    np.testing.assert_array_equal(cm, expected)
