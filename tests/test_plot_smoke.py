"""Plot subsystem smoke sweep: ``.plot()`` must produce a figure for every metric
family (reference gives every metric a ``plot`` method, `metric.py:722-756`,
backed by ``utilities/plot.py``)."""

from __future__ import annotations

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt
import pytest

from tests.test_universal_invariants import CASES

# one representative per output shape family
_PLOT_SAMPLE = [
    "BinaryAccuracy",            # scalar
    "MulticlassAccuracy",        # scalar (macro)
    "MulticlassConfusionMatrix", # matrix -> confusion-matrix plot
    "BinaryROC",                 # curve tuple
    "BinaryPrecisionRecallCurve",
    "MulticlassStatScores",      # per-class vector
    "MeanSquaredError",
    "PeakSignalNoiseRatio",
    "RetrievalMAP",
    "MutualInfoScore",
    "CramersV",
    "MeanMetric",
]


@pytest.mark.parametrize("name", _PLOT_SAMPLE)
def test_plot_returns_figure(name):
    ctor, gen = CASES[name]
    metric = ctor()
    metric.update(*gen())
    fig, ax = metric.plot()
    assert fig is not None and ax is not None
    plt.close(fig)


def test_plot_multiple_values():
    ctor, gen = CASES["BinaryAccuracy"]
    metric = ctor()
    vals = []
    for _ in range(3):
        metric.update(*gen())
        vals.append(metric.compute())
        metric.reset()
    fig, ax = metric.plot(vals)
    assert fig is not None
    plt.close(fig)
