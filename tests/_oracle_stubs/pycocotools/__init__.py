"""pycocotools stub (test infra only) — makes the reference's availability flag True so
its pure-torch bbox mAP oracle can run; mask routines are intentionally absent."""

__version__ = "2.0.8"
