"""Mask RLE routines are not stubbed — bbox-only oracle."""


def _unavailable(*args, **kwargs):
    raise NotImplementedError("pycocotools mask ops are not available in the test stub")


encode = decode = area = iou = toBbox = _unavailable
