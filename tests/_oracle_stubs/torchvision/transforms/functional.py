def resize(*args, **kwargs):
    raise NotImplementedError("torchvision transforms are not available in the test stub")


def to_pil_image(*args, **kwargs):
    raise NotImplementedError("torchvision transforms are not available in the test stub")
