"""transforms stub — just enough surface for the oracle's module-level imports."""


class _Unavailable:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError("torchvision transforms are not available in the test stub")


Compose = Normalize = Resize = CenterCrop = ToTensor = InterpolationMode = _Unavailable

from . import functional  # noqa: E402,F401
