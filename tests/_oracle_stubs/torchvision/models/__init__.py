"""models stub — names only, for the oracle's module-level imports."""


class VGG:  # noqa: D101
    pass


class _ResNetModule:
    def __getattr__(self, name):
        raise NotImplementedError("torchvision models are not available in the test stub")


resnet = _ResNetModule()


def _unavailable(*args, **kwargs):
    raise NotImplementedError("torchvision models are not available in the test stub")


resnet50 = resnet18 = resnet34 = resnet101 = vgg16 = alexnet = squeezenet1_1 = _unavailable


def __getattr__(name):  # any other model name
    return _unavailable
