"""Pure-torch box ops with torchvision-equivalent semantics (test-oracle stub)."""

import torch


def box_area(boxes: torch.Tensor) -> torch.Tensor:
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def _inter_union(boxes1: torch.Tensor, boxes2: torch.Tensor):
    area1 = box_area(boxes1)
    area2 = box_area(boxes2)
    lt = torch.max(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = torch.min(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = (rb - lt).clamp(min=0)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return inter, union


def box_iou(boxes1: torch.Tensor, boxes2: torch.Tensor) -> torch.Tensor:
    inter, union = _inter_union(boxes1, boxes2)
    return inter / union


def generalized_box_iou(boxes1: torch.Tensor, boxes2: torch.Tensor) -> torch.Tensor:
    inter, union = _inter_union(boxes1, boxes2)
    iou = inter / union
    lt = torch.min(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = torch.max(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = (rb - lt).clamp(min=0)
    areai = wh[..., 0] * wh[..., 1]
    return iou - (areai - union) / areai


def _box_diou_iou(boxes1: torch.Tensor, boxes2: torch.Tensor, eps: float = 1e-7):
    inter, union = _inter_union(boxes1, boxes2)
    iou = inter / union
    lt = torch.min(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = torch.max(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = (rb - lt).clamp(min=0)
    diag = wh[..., 0] ** 2 + wh[..., 1] ** 2 + eps
    c1 = (boxes1[:, :2] + boxes1[:, 2:]) / 2
    c2 = (boxes2[:, :2] + boxes2[:, 2:]) / 2
    d = c1[:, None, :] - c2[None, :, :]
    return iou - (d[..., 0] ** 2 + d[..., 1] ** 2) / diag, iou


def distance_box_iou(boxes1: torch.Tensor, boxes2: torch.Tensor, eps: float = 1e-7) -> torch.Tensor:
    diou, _ = _box_diou_iou(boxes1, boxes2, eps)
    return diou


def complete_box_iou(boxes1: torch.Tensor, boxes2: torch.Tensor, eps: float = 1e-7) -> torch.Tensor:
    diou, iou = _box_diou_iou(boxes1, boxes2, eps)
    w1 = boxes1[:, 2] - boxes1[:, 0]
    h1 = boxes1[:, 3] - boxes1[:, 1]
    w2 = boxes2[:, 2] - boxes2[:, 0]
    h2 = boxes2[:, 3] - boxes2[:, 1]
    import math

    v = (4 / math.pi**2) * (torch.atan(w2 / h2)[None, :] - torch.atan(w1 / h1)[:, None]) ** 2
    with torch.no_grad():
        alpha = v / (1 - iou + v + eps)
    return diou - alpha * v


def box_convert(boxes: torch.Tensor, in_fmt: str, out_fmt: str) -> torch.Tensor:
    if in_fmt == out_fmt:
        return boxes
    if out_fmt != "xyxy":
        raise NotImplementedError(f"stub only converts to xyxy, got {out_fmt}")
    a, b, c, d = boxes.unbind(-1)
    if in_fmt == "xywh":
        return torch.stack([a, b, a + c, b + d], dim=-1)
    if in_fmt == "cxcywh":
        return torch.stack([a - c / 2, b - d / 2, a + c / 2, b + d / 2], dim=-1)
    raise NotImplementedError(f"stub cannot convert from {in_fmt}")
