"""Minimal torchvision stub (test infra only) — provides the handful of box ops the
reference oracle imports, implemented with the standard published formulas."""

__version__ = "0.20.0"

from . import ops  # noqa: F401
