"""Minimal test-only stub of ``lightning_utilities`` so the *reference* torchmetrics
package (at /root/reference/src) can be imported as a parity oracle in tests.

Only the four symbols the reference actually imports are provided. This is NOT part of
the shipped framework.
"""

from .core.apply_func import apply_to_collection  # noqa: F401
