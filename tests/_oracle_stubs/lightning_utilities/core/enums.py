from enum import Enum
from typing import List, Optional


class StrEnum(str, Enum):
    """Case-insensitive string enum (stub of lightning_utilities.core.enums.StrEnum)."""

    @classmethod
    def from_str(cls, value: str, source: str = "key") -> "StrEnum":
        if isinstance(value, str):
            if source in ("key", "any"):
                for name, member in cls.__members__.items():
                    if name.lower() == value.lower():
                        return member
            if source in ("value", "any"):
                for member in cls:
                    if str(member.value).lower() == value.lower():
                        return member
        raise ValueError(f"Invalid match: expected one of {cls._allowed_matches(source)}, but got {value}.")

    @classmethod
    def try_from_str(cls, value: str, source: str = "key") -> Optional["StrEnum"]:
        try:
            return cls.from_str(value, source)
        except ValueError:
            return None

    @classmethod
    def _allowed_matches(cls, source: str) -> List[str]:
        keys = [name.lower() for name in cls.__members__]
        values = [str(m.value).lower() for m in cls]
        if source == "key":
            return keys
        if source == "value":
            return values
        return keys + values

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Enum):
            other = other.value
        return isinstance(other, str) and self.value.lower() == other.lower()

    def __hash__(self) -> int:
        return hash(self.value.lower())
