from typing import Any, Callable


def apply_to_collection(data: Any, dtype, function: Callable, *args: Any, **kwargs: Any) -> Any:
    """Recursively apply ``function`` to all elements of ``data`` of type ``dtype``."""
    if isinstance(data, dtype):
        return function(data, *args, **kwargs)
    if isinstance(data, (list, tuple)):
        out = [apply_to_collection(d, dtype, function, *args, **kwargs) for d in data]
        return type(data)(out) if not hasattr(data, "_fields") else type(data)(*out)
    if isinstance(data, dict):
        return {k: apply_to_collection(v, dtype, function, *args, **kwargs) for k, v in data.items()}
    return data
