import importlib.util
import re


def package_available(package_name: str) -> bool:
    try:
        return importlib.util.find_spec(package_name) is not None
    except (ModuleNotFoundError, ValueError):
        return False


class RequirementCache:
    """Boolean-evaluable availability check (stub; ignores version pins)."""

    def __init__(self, requirement: str = "", module: str = None) -> None:
        self.requirement = requirement
        self.module = module

    def _name(self) -> str:
        if self.module:
            return self.module
        return re.split(r"[<>=!\[; ]", self.requirement.strip())[0]

    def __bool__(self) -> bool:
        name = self._name()
        return bool(name) and package_available(name)

    def __str__(self) -> str:
        return f"RequirementCache({self.requirement!r})"

    __repr__ = __str__
