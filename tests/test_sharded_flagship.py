"""Flagship collection across the mesh (VERDICT r2 item 6).

Covers the BASELINE flagship ``MetricCollection([Accuracy, F1, MeanAveragePrecision,
FID])`` as one jitted sharded step on the 8-device CPU mesh, and the
:class:`PaddedDetectionAccumulator` static-shape concat-state design it relies on
(per-device padded buffers + all_gather ≙ reference's padded gather of cat states,
``metric.py:501-540``).
"""

import jax
from torchmetrics_tpu.parallel import shard_map as _shard_map
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.detection import (
    MeanAveragePrecision,
    PaddedDetectionAccumulator,
    pack_detection_batch,
)

from conftest import NUM_DEVICES


def _synth_batch(rng, n_imgs, n_det=(2, 6), n_gt=(1, 5), classes=4):
    preds, target = [], []
    for _ in range(n_imgs):
        nd = int(rng.integers(*n_det))
        ng = int(rng.integers(*n_gt))
        xy = rng.uniform(0, 60, (nd, 2))
        wh = rng.uniform(5, 40, (nd, 2))
        preds.append({
            "boxes": np.concatenate([xy, xy + wh], -1).astype(np.float32),
            "scores": rng.uniform(0, 1, nd).astype(np.float32),
            "labels": rng.integers(0, classes, nd).astype(np.int32),
        })
        xy = rng.uniform(0, 60, (ng, 2))
        wh = rng.uniform(5, 40, (ng, 2))
        target.append({
            "boxes": np.concatenate([xy, xy + wh], -1).astype(np.float32),
            "labels": rng.integers(0, classes, ng).astype(np.int32),
        })
    return preds, target


class TestPaddedDetectionAccumulator:
    def test_pack_roundtrip_matches_direct_update(self):
        rng = np.random.default_rng(0)
        preds, target = _synth_batch(rng, 12)
        acc = PaddedDetectionAccumulator(capacity_images=12, max_detections=8, max_groundtruths=8)
        state = acc.init()
        state = jax.jit(acc.update)(state, *pack_detection_batch(preds, target, 8, 8))
        up_preds, up_target = acc.to_lists(state)

        direct = MeanAveragePrecision()
        direct.update(preds, target)
        packed = MeanAveragePrecision()
        packed.update(up_preds, up_target)
        a, b = direct.compute(), packed.compute()
        np.testing.assert_allclose(float(a["map"]), float(b["map"]), atol=1e-8)
        np.testing.assert_allclose(float(a["mar_100"]), float(b["mar_100"]), atol=1e-8)

    def test_multi_step_cursor(self):
        rng = np.random.default_rng(1)
        acc = PaddedDetectionAccumulator(capacity_images=8, max_detections=8, max_groundtruths=8)
        state = acc.init()
        step = jax.jit(acc.update)
        all_preds, all_target = [], []
        for _ in range(2):
            preds, target = _synth_batch(rng, 4)
            all_preds += preds
            all_target += target
            state = step(state, *pack_detection_batch(preds, target, 8, 8))
        assert int(state["n_images"]) == 8
        up_preds, up_target = acc.to_lists(state)
        assert len(up_preds) == 8
        for got, want in zip(up_preds, all_preds):
            np.testing.assert_allclose(got["boxes"], want["boxes"], atol=0)
            np.testing.assert_allclose(got["scores"], want["scores"], atol=0)

    def test_gathered_sharded_equals_single_process(self):
        """Per-device accumulation + all_gather == one big update (the cat-state sync
        contract, reference metric.py:501-540)."""
        from jax.sharding import PartitionSpec as P

        rng = np.random.default_rng(2)
        n_imgs = NUM_DEVICES * 3
        preds, target = _synth_batch(rng, n_imgs)
        acc = PaddedDetectionAccumulator(capacity_images=3, max_detections=8, max_groundtruths=8)
        batch = pack_detection_batch(preds, target, 8, 8)
        mesh = jax.make_mesh((NUM_DEVICES,), ("dp",))

        def step(*batch):
            state = acc.update(acc.init(), *batch)
            return acc.gather(state, "dp")

        fn = jax.jit(_shard_map(
            step, mesh=mesh, in_specs=tuple(P("dp") for _ in batch), out_specs=P(),
            check_vma=False,
        ))
        gathered = fn(*batch)
        up_preds, up_target = acc.to_lists(gathered)

        sharded = MeanAveragePrecision()
        sharded.update(up_preds, up_target)
        direct = MeanAveragePrecision()
        direct.update(preds, target)
        np.testing.assert_allclose(
            float(sharded.compute()["map"]), float(direct.compute()["map"]), atol=1e-8
        )


class TestFlagshipAcrossMesh:
    def test_flagship_step_and_values(self):
        from __graft_entry__ import _flagship_step_fn

        mesh = jax.make_mesh((NUM_DEVICES,), ("dp",))
        step, args, finalize = _flagship_step_fn(mesh, NUM_DEVICES)
        values = finalize(step(*args))
        assert 0.0 <= float(values["acc"]) <= 1.0
        assert 0.0 <= float(values["f1"]) <= 1.0
        assert 0.0 <= float(values["map"]) <= 1.0
        assert float(values["fid"]) >= 0.0

    def test_flagship_matches_unsharded(self):
        """The sharded flagship's classification values equal a plain host loop over
        the same data."""
        from sklearn.metrics import accuracy_score

        from __graft_entry__ import _flagship_step_fn

        mesh = jax.make_mesh((NUM_DEVICES,), ("dp",))
        step, args, finalize = _flagship_step_fn(mesh, NUM_DEVICES)
        values = finalize(step(*args))
        preds, target = args[0], args[1]
        want = accuracy_score(np.asarray(target), np.asarray(preds).argmax(-1))
        np.testing.assert_allclose(float(values["acc"]), want, atol=1e-7)
