"""Fault-injection: state-integrity guards and graceful degradation.

Covers the recovery paths the reliability layer promises (ISSUE 1):

- NaN/Inf corruption of a named state leaf is caught by guards at the merge and
  sync boundaries (StateCorruptionError; the healthy accumulator is untouched);
- a truncated/partial checkpoint raises StateCorruptionError at restore instead of
  silently loading garbage;
- MetricCollection quarantine: a collection of 4 metrics with one poisoned member
  still computes the other 3, reports the quarantined member's status+error, and
  splits it out of its fused compute group; ``on_error="raise"`` (default) keeps
  today's behavior exactly; ``on_error="skip"`` misses only the failing batch.
"""

from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np
import pytest

import torchmetrics_tpu as tm
from torchmetrics_tpu import MetricCollection, QuarantinedMetric
from torchmetrics_tpu.reliability import (
    ReliabilityConfig,
    poison_state_leaf,
    truncate_state_dict,
    validate_state,
)
from torchmetrics_tpu.utilities.exceptions import StateCorruptionError

pytestmark = pytest.mark.faults

NUM_CLASSES = 5


def _cls_data(n=48, seed=0):
    rng = np.random.default_rng(seed)
    preds = jnp.asarray(rng.normal(size=(n, NUM_CLASSES)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, n, dtype=np.int32))
    return preds, target


# ----------------------------------------------------------------- guards: merge


class TestGuardsAtMerge:
    def test_nan_leaf_in_incoming_shard_caught(self):
        preds, target = _cls_data()
        acc = tm.MulticlassAccuracy(NUM_CLASSES, average="micro", reliability=ReliabilityConfig())
        shard = tm.MulticlassAccuracy(NUM_CLASSES, average="micro")
        acc.update(preds, target)
        shard.update(preds, target)
        before = {k: np.asarray(v) for k, v in acc.metric_state.items()}

        # int states can't hold NaN; an aggregation metric's float state can
        mean = tm.MeanMetric(reliability=ReliabilityConfig())
        mean_shard = tm.MeanMetric()
        mean.update(jnp.asarray([1.0, 2.0]))
        mean_shard.update(jnp.asarray([3.0, 4.0]))
        poison_state_leaf(mean_shard, "mean_value", kind="nan")
        with pytest.raises(StateCorruptionError, match="non-finite"):
            mean.merge_state(mean_shard)
        assert np.isclose(float(mean.compute()), 1.5)  # accumulator untouched

        # shape/dtype damage on an int-state metric is caught structurally
        shard._state["tp"] = shard._state["tp"].astype(jnp.float32) * jnp.nan
        with pytest.raises(StateCorruptionError):
            acc.merge_state(shard)
        after = {k: np.asarray(v) for k, v in acc.metric_state.items()}
        for k in before:
            np.testing.assert_array_equal(before[k], after[k])

    def test_inf_leaf_caught(self):
        m = tm.SumMetric(reliability=ReliabilityConfig())
        other = tm.SumMetric()
        m.update(jnp.asarray(1.0))
        other.update(jnp.asarray(2.0))
        poison_state_leaf(other, "sum_value", kind="inf")
        with pytest.raises(StateCorruptionError, match="non-finite"):
            m.merge_state(other)

    def test_clean_merge_unaffected(self):
        a = tm.MeanMetric(reliability=ReliabilityConfig())
        b = tm.MeanMetric()
        a.update(jnp.asarray([1.0, 2.0]))
        b.update(jnp.asarray([3.0, 4.0]))
        a.merge_state(b)
        assert np.isclose(float(a.compute()), 2.5)

    def test_guards_off_without_config(self):
        """No ReliabilityConfig → merge folds NaN silently (today's behavior)."""
        a, b = tm.MeanMetric(), tm.MeanMetric()
        a.update(jnp.asarray(1.0))
        b.update(jnp.asarray(2.0))
        poison_state_leaf(b, "mean_value")
        a.merge_state(b)  # no raise
        assert np.isnan(float(a.compute()))


# ------------------------------------------------------------------ guards: sync


class TestGuardsAtSync:
    def test_nan_participant_caught_at_sync(self):
        """A NaN contribution from one gather participant corrupts the folded state;
        validate_on_sync raises and the LOCAL state survives for a clean retry path."""

        def nan_gather(value, process_group=None):
            v = jnp.asarray(value)
            bad = jnp.full_like(v.astype(jnp.float32), jnp.nan)
            return [v, bad]

        m = tm.MeanMetric(
            dist_sync_fn=nan_gather,
            distributed_available_fn=lambda: True,
            reliability=ReliabilityConfig(),
        )
        m.update(jnp.asarray([2.0, 4.0]))
        with pytest.raises(StateCorruptionError, match="sync"):
            m.sync()
        assert not m._is_synced
        assert np.isclose(float(np.asarray(m._state["mean_value"])), 6.0)  # local intact (sum-form state)

    def test_validate_state_direct(self):
        m = tm.MeanMetric()
        m.update(jnp.asarray([1.0]))
        validate_state(m)  # clean
        poison_state_leaf(m, "mean_value")
        with pytest.raises(StateCorruptionError, match="mean_value"):
            validate_state(m)


# ----------------------------------------------------------- checkpoint restore


class TestTruncatedCheckpoint:
    def _saved(self):
        preds, target = _cls_data()
        m = tm.MulticlassAccuracy(NUM_CLASSES, average="micro")
        m.update(preds, target)
        m.persistent(True)
        return m, m.state_dict()

    def test_dropped_key_raises(self):
        _, sd = self._saved()
        bad = truncate_state_dict(sd, drop_keys=["fp"])
        fresh = tm.MulticlassAccuracy(NUM_CLASSES, average="micro")
        with pytest.raises(StateCorruptionError, match="truncated"):
            fresh.load_state_dict(bad)

    def test_sliced_array_raises(self):
        _, sd = self._saved()
        bad = truncate_state_dict(sd, slice_keys=["tp"])
        fresh = tm.MulticlassAccuracy(NUM_CLASSES, average="micro")
        with pytest.raises(StateCorruptionError, match="shape"):
            fresh.load_state_dict(bad)

    def test_clean_restore_still_works(self):
        m, sd = self._saved()
        fresh = tm.MulticlassAccuracy(NUM_CLASSES, average="micro")
        fresh.load_state_dict(sd)
        np.testing.assert_array_equal(np.asarray(fresh.compute()), np.asarray(m.compute()))
        assert fresh.update_count == m.update_count

    def test_absent_metric_still_noop(self):
        """A checkpoint that simply doesn't contain this metric loads as a no-op
        (collection checkpoints routinely hold other metrics' keys)."""
        fresh = tm.MulticlassAccuracy(NUM_CLASSES, average="micro")
        fresh.load_state_dict({"someothermetric.total": np.zeros(())})
        assert fresh.update_count == 0

    def test_validate_false_escape_hatch(self):
        _, sd = self._saved()
        bad = truncate_state_dict(sd, drop_keys=["fp"])
        fresh = tm.MulticlassAccuracy(NUM_CLASSES, average="micro")
        fresh.load_state_dict(bad, validate=False)  # forced partial load, no raise
        assert fresh.update_count > 0

    def test_collection_truncated_checkpoint(self):
        preds, target = _cls_data()
        coll = MetricCollection({
            "acc": tm.MulticlassAccuracy(NUM_CLASSES, average="micro"),
            "conf": tm.MulticlassConfusionMatrix(NUM_CLASSES),
        })
        coll.update(preds, target)
        coll.persistent(True)
        sd = coll.state_dict()
        bad = truncate_state_dict(sd, drop_keys=["acc.tp"])
        fresh = MetricCollection({
            "acc": tm.MulticlassAccuracy(NUM_CLASSES, average="micro"),
            "conf": tm.MulticlassConfusionMatrix(NUM_CLASSES),
        })
        with pytest.raises(StateCorruptionError, match="truncated"):
            fresh.load_state_dict(bad)

    def test_restore_finiteness_opt_in(self):
        m = tm.MeanMetric()
        m.update(jnp.asarray([1.0]))
        m.persistent(True)
        sd = m.state_dict()
        sd["mean_value"] = np.asarray(np.nan, np.float32)
        # default: structural checks only → loads
        loose = tm.MeanMetric()
        loose.load_state_dict(dict(sd))
        # opted in: finiteness scan rejects
        strict = tm.MeanMetric(reliability=ReliabilityConfig())
        with pytest.raises(StateCorruptionError, match="non-finite"):
            strict.load_state_dict(dict(sd))


# ------------------------------------------------------------------- quarantine


class _PoisonAfter(tm.Metric):
    """Healthy for the first N updates, then raises — a realistically delayed
    poisoning (e.g. a NaN logit arriving mid-eval)."""

    def __init__(self, healthy_updates=1, **kw):
        super().__init__(**kw)
        self.add_state("n", default=np.zeros(()), dist_reduce_fx="sum")
        self.healthy_updates = healthy_updates

    def _batch_state(self, preds, target):
        return {"n": jnp.ones(())}

    def _prepare_inputs(self, *args, **kwargs):
        if self._update_count >= self.healthy_updates:
            raise RuntimeError("poisoned member: simulated in-metric failure")
        return args, kwargs

    def _compute(self, state):
        return state["n"]


def _quad_collection(on_error, poison_kw=None, **coll_kw):
    return MetricCollection(
        {
            "acc": tm.MulticlassAccuracy(NUM_CLASSES, average="micro"),
            "f1": tm.MulticlassF1Score(NUM_CLASSES, average="macro"),
            "conf": tm.MulticlassConfusionMatrix(NUM_CLASSES),
            "poison": _PoisonAfter(**(poison_kw or {})),
        },
        on_error=on_error,
        **coll_kw,
    )


class TestQuarantine:
    def test_three_of_four_still_compute(self):
        preds, target = _cls_data()
        ref = _quad_collection("raise", poison_kw={"healthy_updates": 99})
        coll = _quad_collection("quarantine")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            for _ in range(3):
                ref.update(preds, target)
                coll.update(preds, target)
        assert list(coll.quarantined) == ["poison"]
        out = coll.compute()
        ref_out = ref.compute()
        for key in ("acc", "f1", "conf"):
            np.testing.assert_array_equal(np.asarray(out[key]), np.asarray(ref_out[key]), err_msg=key)
        status = out["poison"]
        assert isinstance(status, QuarantinedMetric)
        assert status.status == "quarantined"
        assert status.stage == "update"
        assert "poisoned member" in status.error
        assert status.update_count == 1  # froze after its one healthy update

    def test_forward_surfaces_status(self):
        preds, target = _cls_data()
        coll = _quad_collection("quarantine")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            first = coll.forward(preds, target)
            second = coll.forward(preds, target)
        assert not isinstance(first["poison"], QuarantinedMetric)
        assert isinstance(second["poison"], QuarantinedMetric)
        assert not isinstance(second["acc"], QuarantinedMetric)

    def test_raise_mode_preserves_behavior(self):
        preds, target = _cls_data()
        coll = _quad_collection("raise")
        coll.update(preds, target)
        with pytest.raises(RuntimeError, match="poisoned member"):
            coll.update(preds, target)

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError, match="on_error"):
            _quad_collection("explode")

    def test_compute_group_split_keeps_members_alive(self):
        """Two metrics sharing one compute group (identical states): when the group
        LEADER is quarantined, the surviving member takes over mid-batch and its
        values match an unfaulted run exactly."""
        preds, target = _cls_data()

        coll = MetricCollection(
            {
                # alphabetical insert order makes the poisoned metric the leader of
                # the merged {a_poison, recall} group (same tp/fp/tn/fn states)
                "a_poison": tm.MulticlassRecall(NUM_CLASSES, average="micro"),
                "recall": tm.MulticlassRecall(NUM_CLASSES, average="micro"),
                "conf": tm.MulticlassConfusionMatrix(NUM_CLASSES),
            },
            on_error="quarantine",
        )
        ref = MetricCollection({
            "recall": tm.MulticlassRecall(NUM_CLASSES, average="micro"),
            "conf": tm.MulticlassConfusionMatrix(NUM_CLASSES),
        })
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            coll.update(preds, target)
            ref.update(preds, target)
            groups = {frozenset(m) for m in coll.compute_groups.values()}
            assert frozenset({"a_poison", "recall"}) in groups
            # poison the leader from here on
            coll["a_poison"]._prepare_inputs = _raise_prepare
            coll.update(preds, target)
            ref.update(preds, target)
        assert list(coll.quarantined) == ["a_poison"]
        groups = {frozenset(m) for m in coll.compute_groups.values()}
        assert frozenset({"recall"}) in groups  # split: survivor runs alone
        out = coll.compute()
        ref_out = ref.compute()
        np.testing.assert_array_equal(np.asarray(out["recall"]), np.asarray(ref_out["recall"]))
        np.testing.assert_array_equal(np.asarray(out["conf"]), np.asarray(ref_out["conf"]))
        # frozen at its last good state: one update's worth
        assert isinstance(out["a_poison"], QuarantinedMetric)
        assert out["a_poison"].update_count == 1

    def test_reset_lifts_quarantine(self):
        preds, target = _cls_data()
        coll = _quad_collection("quarantine")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            coll.update(preds, target)
            coll.update(preds, target)
        assert coll.quarantined
        coll.reset()
        assert not coll.quarantined
        coll["poison"].healthy_updates = 99  # healed
        coll.update(preds, target)
        out = coll.compute()
        assert not isinstance(out["poison"], QuarantinedMetric)

    def test_skip_mode_misses_only_failing_batch(self):
        preds, target = _cls_data()
        coll = _quad_collection("skip", poison_kw={"healthy_updates": 1})
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            coll.update(preds, target)  # healthy
            coll.update(preds, target)  # poison raises once → skipped, not frozen
            coll["poison"].healthy_updates = 99  # heals after the one failure
            coll.update(preds, target)  # healthy again
        assert not coll.quarantined
        assert coll["poison"].update_count == 2  # missed exactly the failing batch
        assert coll["acc"].update_count == 3

    def test_compute_failure_quarantines(self):
        preds, target = _cls_data()

        class BadCompute(tm.Metric):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("n", default=np.zeros(()), dist_reduce_fx="sum")

            def _batch_state(self, preds, target):
                return {"n": jnp.ones(())}

            def _compute(self, state):
                raise RuntimeError("compute blew up")

        coll = MetricCollection(
            {"acc": tm.MulticlassAccuracy(NUM_CLASSES, average="micro"), "bad": BadCompute()},
            on_error="quarantine",
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            coll.update(preds, target)
            out = coll.compute()
        assert isinstance(out["bad"], QuarantinedMetric)
        assert out["bad"].stage == "compute"
        assert not isinstance(out["acc"], QuarantinedMetric)
        assert "bad" in coll.quarantined


def _raise_prepare(*args, **kwargs):
    raise RuntimeError("poisoned member: leader fails post-grouping")


# ------------------------------------------------- review regressions (hardening)


class TestReviewRegressions:
    def test_merge_guard_catches_corrupt_local_accumulator(self):
        """The LOCAL side is validated too — a merged-dict validation would let the
        incoming (clean) keys shadow a NaN-corrupted accumulator and launder it."""
        acc = tm.MeanMetric(reliability=ReliabilityConfig())
        clean = tm.MeanMetric()
        acc.update(jnp.asarray([1.0, 2.0]))
        clean.update(jnp.asarray([3.0, 4.0]))
        poison_state_leaf(acc, "mean_value", kind="nan")
        with pytest.raises(StateCorruptionError, match=r"local.*non-finite|non-finite"):
            acc.merge_state(clean)

    def test_quarantined_state_survives_survivor_donated_updates(self):
        """Detaching a member copies its BUFFERS, not just containers: the survivor's
        donated jitted updates must not delete the frozen metric's arrays."""
        preds, target = _cls_data()
        coll = MetricCollection(
            {
                "a_poison": tm.MulticlassRecall(NUM_CLASSES, average="micro"),
                "recall": tm.MulticlassRecall(NUM_CLASSES, average="micro"),
            },
            on_error="quarantine",
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            coll.update(preds, target)
            frozen_before = {k: np.asarray(v) for k, v in coll["a_poison"]._state.items()}
            coll["a_poison"]._prepare_inputs = _raise_prepare
            coll.update(preds, target)  # quarantines a_poison, survivor takes over
            coll.update(preds, target)  # survivor's donated update must not touch it
        # frozen state is still readable (not deleted buffers) and unchanged
        coll.persistent(True)
        sd = coll.state_dict()
        for k, v in frozen_before.items():
            np.testing.assert_array_equal(np.asarray(coll["a_poison"]._state[k]), v)
            np.testing.assert_array_equal(np.asarray(sd[f"a_poison.{k}"]), v)

    def test_skip_mode_with_explicit_compute_groups_keeps_updating(self):
        """A skip-mode failure inside an explicit compute_groups list re-adds the
        metric as its own singleton group — it misses only the failing batch."""
        preds, target = _cls_data()
        coll = MetricCollection(
            {
                "acc": tm.MulticlassAccuracy(NUM_CLASSES, average="micro"),
                "poison": _PoisonAfter(healthy_updates=1),
            },
            compute_groups=[["acc"], ["poison"]],
            on_error="skip",
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            coll.update(preds, target)  # healthy
            coll.update(preds, target)  # poison raises once -> skipped
            coll["poison"].healthy_updates = 99
            coll.update(preds, target)  # must update again (not silently dropped)
        assert coll["poison"]._update_count == 2
        assert coll["acc"]._update_count == 3

    def test_collection_with_wrapper_restores(self):
        """Wrapper metrics accept the validate= kwarg threaded through
        MetricCollection.load_state_dict (restore of wrapper-containing
        collections must not TypeError)."""
        from torchmetrics_tpu.wrappers import ClasswiseWrapper, MinMaxMetric

        preds, target = _cls_data()
        coll = MetricCollection(
            {
                "cw": ClasswiseWrapper(tm.MulticlassAccuracy(NUM_CLASSES, average=None)),
                "mm": MinMaxMetric(tm.MulticlassAccuracy(NUM_CLASSES, average="micro")),
            }
        )
        coll.update(preds, target)
        coll.persistent(True)
        sd = coll.state_dict()
        fresh = MetricCollection(
            {
                "cw": ClasswiseWrapper(tm.MulticlassAccuracy(NUM_CLASSES, average=None)),
                "mm": MinMaxMetric(tm.MulticlassAccuracy(NUM_CLASSES, average="micro")),
            }
        )
        fresh.load_state_dict(sd)
        got, want = fresh.compute(), coll.compute()
        for k in want:
            np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]))

    def test_healthy_degrading_collection_keeps_groups_across_reset(self):
        """A skip/quarantine collection that never failed keeps its fused compute
        groups through reset() (no per-epoch group re-derivation tax)."""
        preds, target = _cls_data()
        coll = MetricCollection(
            {
                "prec": tm.MulticlassPrecision(NUM_CLASSES, average="micro"),
                "rec": tm.MulticlassRecall(NUM_CLASSES, average="micro"),
            },
            on_error="skip",
        )
        coll.update(preds, target)
        groups_before = {frozenset(m) for m in coll.compute_groups.values()}
        assert frozenset({"prec", "rec"}) in groups_before
        coll.reset()
        assert coll._groups_checked  # fused groups survived the reset
        assert {frozenset(m) for m in coll.compute_groups.values()} == groups_before
        coll.update(preds, target)
        assert {frozenset(m) for m in coll.compute_groups.values()} == groups_before

    def test_merge_folds_healthy_groupmate_when_incoming_leader_quarantined(self):
        """An incoming collection that quarantined the fused group's LEADER must not
        cost the merge its healthy group-mates' contributions — the fold routes
        through the first member healthy on both sides."""
        preds_a, target_a = _cls_data(seed=1)
        preds_b, target_b = _cls_data(seed=2)

        def _pair(on_error):
            return MetricCollection(
                {
                    "a_poison": tm.MulticlassRecall(NUM_CLASSES, average="micro"),
                    "recall": tm.MulticlassRecall(NUM_CLASSES, average="micro"),
                },
                on_error=on_error,
            )

        shard_a, shard_b = _pair("quarantine"), _pair("quarantine")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            shard_a.update(preds_a, target_a)
            shard_b.update(preds_b, target_b)
            # B quarantines the group leader; its 'recall' keeps the full stream
            shard_b["a_poison"]._prepare_inputs = _raise_prepare
            shard_b.update(preds_b, target_b)
            shard_a.update(preds_a, target_a)
            shard_a.merge_state(shard_b)
        ref = tm.MulticlassRecall(NUM_CLASSES, average="micro")
        for p, t in ((preds_a, target_a), (preds_a, target_a), (preds_b, target_b), (preds_b, target_b)):
            ref.update(p, t)
        np.testing.assert_array_equal(
            np.asarray(shard_a["recall"].compute()), np.asarray(ref.compute())
        )

    def test_running_truncated_checkpoint_raises(self):
        """Running wrapper honors validate=: a lost ring key raises
        StateCorruptionError instead of a bare KeyError / silent empty resume."""
        from torchmetrics_tpu.reliability import truncate_state_dict
        from torchmetrics_tpu.wrappers import Running

        run = Running(tm.MeanMetric(), window=3)
        for v in (1.0, 2.0, 3.0):
            run.update(jnp.asarray(v))
        run.persistent(True)
        sd = run.state_dict()
        ring_keys = [k for k in sd if k.startswith("_ring0.")]
        assert ring_keys, sorted(sd)
        with pytest.raises(StateCorruptionError, match="truncated"):
            Running(tm.MeanMetric(), window=3).load_state_dict(
                truncate_state_dict(sd, drop_keys=ring_keys)
            )
        with pytest.raises(StateCorruptionError, match="truncated"):
            Running(tm.MeanMetric(), window=3).load_state_dict(
                truncate_state_dict(sd, drop_keys=["_ring_len"])
            )

    def test_running_missing_update_count_raises(self):
        """A checkpoint that kept the ring but lost '_wrapper_update_count' is
        truncated too — StateCorruptionError, not a bare KeyError; the target
        wrapper is left untouched."""
        from torchmetrics_tpu.wrappers import Running

        run = Running(tm.MeanMetric(), window=3)
        for v in (1.0, 2.0, 3.0):
            run.update(jnp.asarray(v))
        run.persistent(True)
        sd = run.state_dict()
        fresh = Running(tm.MeanMetric(), window=3)
        with pytest.raises(StateCorruptionError, match="truncated"):
            fresh.load_state_dict(truncate_state_dict(sd, drop_keys=["_wrapper_update_count"]))
        assert fresh._ring == [] and fresh._update_count == 0

    def test_mixed_persistence_checkpoint_loads_clean(self):
        """A metric whose states mix persistent and non-persistent flags saves a
        legitimate PARTIAL checkpoint — the '_saved_states' manifest keeps the
        truncation guard from rejecting it, while an actually-lost key still raises."""

        class Mixed(tm.Metric):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum", persistent=True)
                self.add_state("scratch", default=np.zeros(()), dist_reduce_fx="sum", persistent=False)

            def _batch_state(self, x):
                return {"total": jnp.asarray(x), "scratch": jnp.asarray(x)}

            def _compute(self, state):
                return state["total"]

        m = Mixed()
        m.update(jnp.asarray(2.0))
        sd = m.state_dict()
        assert "total" in sd and "scratch" not in sd  # partial by design
        fresh = Mixed()
        fresh.load_state_dict(sd)  # must NOT raise "truncated"
        assert float(np.asarray(fresh._state["total"])) == 2.0
        with pytest.raises(StateCorruptionError, match="truncated"):
            Mixed().load_state_dict(truncate_state_dict(sd, drop_keys=["total"]))

    def test_reset_after_degradation_dealiases_group_state(self):
        """reset() on a degraded collection must break the state-dict aliasing of
        formerly-fused members: the next (ungrouped) update runs every metric
        separately, and a still-shared dict would absorb each batch twice."""
        preds, target = _cls_data()
        coll = MetricCollection(
            {
                "prec": tm.MulticlassPrecision(NUM_CLASSES, average="micro"),
                "rec": tm.MulticlassRecall(NUM_CLASSES, average="micro"),
                "poison": _PoisonAfter(healthy_updates=1),
            },
            on_error="skip",
        )
        ref = tm.MulticlassPrecision(NUM_CLASSES, average="micro")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            coll.update(preds, target)  # fuses prec+rec
            coll.update(preds, target)  # poison fails -> _degraded
            coll.reset()
            coll.update(preds, target)  # ungrouped pass: must not double-count
            ref.update(preds, target)
        assert coll["prec"]._state is not coll["rec"]._state or coll.compute_groups
        np.testing.assert_array_equal(
            np.asarray(coll["prec"].compute()), np.asarray(ref.compute())
        )

    def test_first_batch_failure_does_not_fuse_rolled_back_defaults(self):
        """A first-batch failure under 'skip' rolls metrics back to identical default
        states — group derivation must wait for a clean batch instead of fusing
        distinct metrics whose states merely LOOK equal."""
        preds, target = _cls_data()

        class FailFirst(_PoisonAfter):
            def _prepare_inputs(self, *args, **kwargs):
                self.calls = getattr(self, "calls", 0) + 1
                if self.calls == 1:
                    raise RuntimeError("bad first batch")
                return args, kwargs

        coll = MetricCollection(
            {
                "a": FailFirst(healthy_updates=1),
                "b": FailFirst(healthy_updates=1),
            },
            on_error="skip",
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            coll.update(preds, target)  # both fail -> rolled back to defaults
        assert not coll._groups_checked  # derivation deferred, nothing fused
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            coll.update(preds, target)  # clean batch derives groups normally
        assert coll["a"]._update_count == 1 and coll["b"]._update_count == 1

    def test_sync_tolerates_legit_nan_in_cat_state(self):
        """Finiteness guards are scoped to aggregate leaves: raw cat states carrying
        NaN by construction (masked preds) must survive a validated sync."""

        def fake_gather(value, process_group=None):
            v = jnp.asarray(value)
            return [v, v]

        m = tm.CatMetric(
            dist_sync_fn=fake_gather,
            distributed_available_fn=lambda: True,
            reliability=ReliabilityConfig(),
        )
        m.update(jnp.asarray([1.0, jnp.nan, 3.0]))  # legit NaN in raw data
        m.sync()  # must NOT raise StateCorruptionError
        assert m._is_synced
